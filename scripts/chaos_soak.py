"""Seeded chaos soak: the full train → checkpoint → serve cycle under
injected faults at every reliability hook site.

What the Spark reference proved by killing executors under a running
job, this harness proves by arming every failpoint in the codebase
(``tpu_sgd/reliability/failpoints.py``) during a supervised streamed-SGD
run and a hot-reloading serve phase, then asserting the three
reliability invariants:

1. **No corruption** — the chaos run's final weights and loss history
   are BITWISE identical to a fault-free reference run (f32 wire;
   every iteration is deterministic in ``(seed, i)``, so crash-resume
   replays the exact trajectory), the checkpoint directory restores
   cleanly, and the event log parses back (tolerating the deliberately
   torn tail line this script appends).
2. **No hang** — every phase runs under a ``Deadline``; every serving
   future resolves within a bounded timeout.
3. **Degraded, never down** — serving answers correctly through
   injected reload faults (previous-good model + circuit breaker), and
   ``healthz`` stays consistent.

Deterministic by construction: all fault schedules draw from
``--seed``-derived streams, so a failure reproduces exactly.

Usage::

    python scripts/chaos_soak.py --seed 0 [--iters 40] [--quiet]
        [--trace chaos_trace.jsonl] [--slo slo.json] [--chrome t.json]

Observability (ISSUE 8): every soak emits a trace — the soak's event
log IS the trace file (``--trace``; listener events, ``trace_span`` /
``trace_event`` / ``metric_counters`` records, and the serve_reload
stream interleave on ONE lock-serialized JSONL, torn tail included),
and after the invariants hold the CLI pipes it straight through
``python -m tpu_sgd.obs.report``: a Chrome trace-event export
(``--chrome``, Perfetto-loadable) plus an SLO verdict (``--slo``, or
the built-in :data:`DEFAULT_SLOS` asserting the soak really exercised
train windows, checkpoint saves, and serve batches).  Exit code 0 =
invariants held AND every SLO passed; an SLO violation exits nonzero
through the report CLI's own exit-code contract.

The live metrics plane (ISSUE 13) is armed over the same trace: the
detector engine rides 0.25s windows (the overload burst must trip the
shed-rate rule; a dedicated straggler cell — phase 1e, a kill with a
long deterministic rejoin backoff — must trip the replica-straggler
rule; both gated by ``alert_count`` SLOs), and the flight recorder
dumps its ring + window snapshots to ``<trace>.flightrec.jsonl`` on
every alert transition, schema-validated before the report runs.

The HA store layer (ISSUE 14) is soaked in phase 1f: a τ=0 fleet with
one standby has its PRIMARY STORE killed mid-round (bitwise vs
fault-free asserted — failover is a replay), and a τ=2 compressed
fleet carries one worker PARTITIONED through a full failover (fenced
stale-epoch pushes counted, matched objective, zero lost EF mass);
the ``replica.failover`` span, its downtime bound, and the failover
detector's typed alert are all gated by the default SLOs.

The integrity plane (ISSUE 15) is soaked in phase 1g: ``corrupt_prob``
armed at every checksummed wire (dense/sparse chunks, push payloads,
delta-log records; EF segments verify at their extraction boundary on
the same runs) with healed runs asserted BITWISE vs fault-free; a
checksums-off poison cell whose NaN payloads the store's admission
gate rejects whole at matched loss; and a forced weight-corruption
cell that ROLLS BACK through epoch fencing to the last good
checkpoint, replaying bitwise — all gated by the ``integrity-*``
default SLOs (corruption injected, zero unhealed, detector tripped,
rollback span traced).

Exit code 0 = all invariants held.  Also exposed as the ``slow``-marked
``tests/test_reliability.py::test_chaos_soak`` (excluded from tier-1).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

#: the built-in SLO document the CLI evaluates when ``--slo`` is not
#: given: not latency theater (this 2-core harness drowns wall clocks
#: in noise — counts are the truth), but structural assertions that the
#: soak's trace really contains the full cycle it claims to have
#: soaked.  A soak that silently skipped a phase fails its SLO gate.
DEFAULT_SLOS = {"slos": [
    {"name": "train-windows-fired", "metric": "span_count",
     "span": "train.window", "min": 1},
    {"name": "checkpoint-saves-traced", "metric": "span_count",
     "span": "checkpoint.save", "min": 1},
    {"name": "serve-batches-traced", "metric": "span_count",
     "span": "serve.batch", "min": 1},
    {"name": "no-serve-stall", "metric": "span_max_s",
     "span": "serve.batch", "max": 30.0},
    {"name": "callback-windows-counted", "metric": "counter",
     "counter": "train.io_callback", "min": 1},
    {"name": "replica-pushes-counted", "metric": "counter",
     "counter": "replica.push.accepted", "min": 1},
    # the overload-burst phase (2b) deliberately drowns a 16-deep queue,
    # so the premium lane DOES shed there — but displacement (shadow and
    # batch evicted first) must keep its typed-rejection fraction under
    # the bound while the low lanes absorb the loss (ISSUE 12; the
    # scenario harness gates the tighter production bound of 0.5)
    {"name": "serve-sheds-bounded", "metric": "lane_shed_fraction",
     "lane": "interactive", "max": 0.9},
    # the detectors really detected (ISSUE 13): the overload burst must
    # trip the shed-rate rule and the dedicated straggler cell (phase
    # 1e) the replica-straggler rule — typed obs_alert records on the
    # trace, not grepped log lines
    {"name": "shed-rate-alert-fired", "metric": "alert_count",
     "rule": "shed-rate", "min": 1},
    {"name": "straggler-alert-fired", "metric": "alert_count",
     "rule": "replica-straggler", "min": 1},
    # the HA store failover (phase 1f): the promotion really ran (its
    # span is the downtime surface — bounded loosely here, the 2-core
    # walls are weather) and the failover detector emitted its typed
    # alert on this trace
    {"name": "store-failover-traced", "metric": "span_count",
     "span": "replica.failover", "min": 1},
    {"name": "failover-downtime-bounded", "metric": "span_max_s",
     "span": "replica.failover", "max": 30.0},
    {"name": "failover-alert-fired", "metric": "alert_count",
     "rule": "failover", "min": 1},
    # the integrity plane (ISSUE 15, phase 1g): corruption was really
    # injected at the checksummed wires AND every detected frame healed
    # — integrity.unhealed counts only corruption that escaped every
    # healing layer, and the soak's own bitwise asserts are the ground
    # truth this counter mirrors; the detector must have turned the
    # corrupt frames into typed alerts, and the forced weight-poison
    # cell must have rolled back under its span
    {"name": "integrity-corruption-injected", "metric": "counter",
     "counter": "integrity.corrupt", "min": 1},
    {"name": "integrity-zero-unhealed", "metric": "counter",
     "counter": "integrity.unhealed", "max": 0},
    {"name": "integrity-alert-fired", "metric": "alert_count",
     "rule": "integrity", "min": 1},
    {"name": "integrity-rollback-traced", "metric": "span_count",
     "span": "integrity.rollback", "min": 1},
]}


def _make_data(seed: int, n: int = 768, d: int = 12):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (X @ w_true + 0.01 * rng.normal(size=n)).astype(np.float32)
    return X, y


def _make_opt(iters: int, sampling: str, retry=None):
    from tpu_sgd.optimize.gradient_descent import GradientDescent

    # superstep=4 runs the FUSED executor under fire: crash-resume
    # restarts land mid-grid (checkpoint cadence 5, K=4), so superstep
    # regrouping after a resume is soaked too — the per-iteration math
    # is grouping-independent, so the bitwise invariant must still hold
    opt = (GradientDescent()
           .set_num_iterations(iters).set_step_size(0.1)
           .set_mini_batch_fraction(0.5).set_sampling(sampling)
           .set_convergence_tol(0.0).set_seed(7)
           .set_host_streaming(True).set_superstep(4))
    if retry is not None:
        opt.set_ingest_options(retry=retry)
    return opt


def _make_resident_opt(iters: int, retry=None):
    from tpu_sgd.optimize.gradient_descent import GradientDescent

    # full-batch feed + residency(2): the WHOLE run is one compiled
    # while_loop dispatch; the host touches the run only through the
    # cadence io_callback (optimize/resident_driver.py) — which is
    # exactly the surface this phase injects faults into
    opt = (GradientDescent()
           .set_num_iterations(iters).set_step_size(0.1)
           .set_mini_batch_fraction(1.0).set_convergence_tol(0.0)
           .set_seed(7).set_host_streaming(True)
           .set_superstep(4).set_residency(2))
    if retry is not None:
        opt.set_ingest_options(retry=retry)
    return opt


def soak(seed: int = 0, iters: int = 40, verbose: bool = True,
         trace_path: str | None = None) -> dict:
    """Run the soak; returns a summary dict.  Raises AssertionError on
    any invariant violation, TimeoutError/DeadlineExceeded on a hang.

    ``trace_path`` routes the soak's event log to a PERSISTENT file and
    turns the observability layer on over it (``tpu_sgd.obs``: spans +
    runtime counters share the log as a caller-owned sink), so the
    returned file is a complete soak trace — including the deliberately
    torn tail line phase 3 appends, which ``obs.report`` must (and
    does) parse past via the shared ``read()`` semantics."""
    from tpu_sgd import obs
    from tpu_sgd.models import LinearRegressionModel
    from tpu_sgd.reliability import (
        CircuitBreaker,
        Deadline,
        HealthMonitor,
        RetryPolicy,
        TrainingSupervisor,
        fail_nth,
        fail_prob,
        inject_faults,
        inject_latency,
    )
    from tpu_sgd.reliability import failpoints as fp
    from tpu_sgd.serve import ModelRegistry, Server
    from tpu_sgd.utils.checkpoint import CheckpointManager
    from tpu_sgd.utils.events import CollectingListener, JsonLinesEventLog

    def say(msg):
        if verbose:
            print(f"[chaos_soak seed={seed}] {msg}")

    X, y = _make_data(seed)
    w0 = np.zeros(X.shape[1], np.float32)
    summary = {"seed": seed, "iters": iters}

    # ---- reference: fault-free streamed run ------------------------------
    w_ref, h_ref = _make_opt(iters, "sliced").optimize_with_history(
        (X, y), w0)
    w_ref = np.asarray(w_ref)

    with tempfile.TemporaryDirectory() as work:
        ckpt_dir = os.path.join(work, "ckpt")
        log_path = trace_path or os.path.join(work, "events.jsonl")
        if trace_path is not None and os.path.exists(trace_path):
            # the log opens in append mode and every soak ENDS with a
            # deliberately torn tail line — a rerun must start from an
            # empty trace or its first record would concatenate onto the
            # previous run's torn tail into one malformed interior line
            # (which read() correctly refuses to tolerate)
            os.truncate(trace_path, 0)
        event_log = JsonLinesEventLog(log_path, fsync=True)
        flight_path = (trace_path + ".flightrec.jsonl"
                       if trace_path is not None else None)
        if trace_path is not None:
            # ONE stream: listener events, serve_reload records, and
            # the obs layer's trace_span/trace_event/metric_counters
            # all interleave on the caller-owned log — the spelling
            # tests/test_obs.py pins and obs.report consumes whole.
            # ISSUE 13: the detector engine rides the 0.25s windowed
            # time-series (shed-rate must trip under the burst, the
            # straggler rule in phase 1e) and the flight recorder arms
            # over the same stream — a stale dump from a previous run
            # must not satisfy this run's schema check
            if os.path.exists(flight_path):
                os.remove(flight_path)
            obs.enable(event_log, detect=True, window_s=0.25,
                       flightrec=flight_path)
        quarantined = []
        manager = CheckpointManager(
            ckpt_dir,
            on_corruption=lambda p, q, e: quarantined.append(q or p))

        # ---- phase 1: supervised training under fire ---------------------
        deadline = Deadline(300.0)
        opt = _make_opt(
            iters, "sliced",
            retry=RetryPolicy(max_attempts=4, base_backoff_s=0.002,
                              seed=seed + 10))
        sup = TrainingSupervisor(
            opt, checkpoint_manager=manager, checkpoint_every=5,
            retry=RetryPolicy(max_attempts=200, base_backoff_s=0.002,
                              seed=seed + 11),
            listener=event_log, install_signal_handlers=False)
        train_faults = {
            # iteration-body crashes: lose up to checkpoint_every
            # iterations, resume replays them
            "optimize.streamed.step": fail_prob(0.05, seed=seed + 1),
            # transfer faults: healed in place by the ingest retry
            "io.device_put": fail_prob(0.05, seed=seed + 2),
            # straggler simulation on the feed worker (latency only)
            "io.prefetch.produce": inject_latency(2.0, prob=0.2,
                                                 seed=seed + 3),
            # superchunk-assembly faults: healed by the same ingest
            # retry (the sample is deterministic in (seed, i), so a
            # re-assembled superchunk is identical)
            "io.superstep": fail_prob(0.05, seed=seed + 6),
            # a save fault crashes the run BEFORE any byte is written
            "checkpoint.save": fail_prob(0.04, seed=seed + 4),
            # a load fault during resume: restore() quarantines and
            # falls back to an older checkpoint — more replay, same
            # trajectory
            "checkpoint.load": fail_prob(0.10, seed=seed + 5),
        }
        with inject_faults(train_faults):
            result = sup.run((X, y), w0)
            summary["train_hits"] = {k: fp.hits(k) for k in train_faults}
            summary["train_triggers"] = {
                k: fp.triggers(k) for k in train_faults}
        deadline.check("chaos training phase")
        missed = [
            k for k, n in summary["train_hits"].items()
            if n == 0
            # a lucky seed with zero crashes never resumes, so the
            # restore-side load hook legitimately goes unvisited
            and not (k == "checkpoint.load" and result.attempts == 1)
        ]
        assert not missed, f"hook sites never reached: {missed}"
        assert result.completed, f"soak run did not complete: {result.status}"
        summary["train_attempts"] = result.attempts
        summary["checkpoints_quarantined"] = len(quarantined)
        say(f"training survived: {result.attempts} attempt(s), "
            f"{len(quarantined)} checkpoint(s) quarantined, "
            f"triggers={summary['train_triggers']}")

        # invariant 1: bitwise equality with the fault-free run
        np.testing.assert_array_equal(
            np.asarray(result.weights), w_ref,
            err_msg="chaos weights diverged from the fault-free run")
        np.testing.assert_array_equal(
            result.loss_history, h_ref,
            err_msg="chaos loss history diverged")
        say("final weights/losses BITWISE equal to the fault-free run")

        # the checkpoint directory restores the completed run
        state = manager.restore()
        assert state is not None and state["iteration"] == iters, (
            "checkpoint directory does not restore the final iteration")
        np.testing.assert_array_equal(state["weights"], w_ref)

        # a mid-run kill + bare resume: arm a one-shot crash, run an
        # UNsupervised optimizer against a fresh dir, then resume
        kill_dir = os.path.join(work, "ckpt_kill")
        opt_kill = _make_opt(iters, "sliced")
        opt_kill.set_checkpoint(CheckpointManager(kill_dir), every=5)
        # the iteration-body site fires once per DISPATCH — one per
        # superstep under fusion — so aim the one-shot kill at the
        # mid-run dispatch, which lands the resume mid-grid (cadence 5,
        # K=4: superstep regrouping under test)
        crash_at = max(2, (iters // opt_kill.superstep) // 2)
        with inject_faults(
                {"optimize.streamed.step": fail_nth(crash_at)}):
            try:
                opt_kill.optimize_with_history((X, y), w0)
                raise AssertionError("injected kill did not fire")
            except fp.FaultInjected:
                pass
        w_res, h_res = opt_kill.optimize_with_history((X, y), w0)
        np.testing.assert_array_equal(np.asarray(w_res), w_ref)
        np.testing.assert_array_equal(h_res, h_ref)
        say(f"kill at dispatch {crash_at} + bare resume: bitwise equal")

        # ---- phase 1b: DEVICE-RESIDENT driver under fire -----------------
        # the resident path's only steady-state host surface is the
        # cadence window callback: arm its failpoint (heals through the
        # ingest RetryPolicy inside the callback, before any bookkeeping
        # mutates), plus save/load and the dispatch-body site, and
        # require the three invariants again — mid-run preempt lands at
        # a cadence-window boundary, resumes, and stays bitwise
        deadline = Deadline(300.0)
        w_res_ref, h_res_ref = _make_resident_opt(iters) \
            .optimize_with_history((X, y), w0)
        w_res_ref = np.asarray(w_res_ref)
        res_dir = os.path.join(work, "ckpt_resident")
        res_mgr = CheckpointManager(res_dir)
        res_opt = _make_resident_opt(
            iters, retry=RetryPolicy(max_attempts=4, base_backoff_s=0.002,
                                     seed=seed + 20))
        res_sup = TrainingSupervisor(
            res_opt, checkpoint_manager=res_mgr, checkpoint_every=5,
            retry=RetryPolicy(max_attempts=200, base_backoff_s=0.002,
                              seed=seed + 21),
            listener=event_log, install_signal_handlers=False)
        resident_faults = {
            # the window callback itself: healed by the ingest retry
            # inside the callback; an exhausted retry stashes the error,
            # stops the loop, and the supervisor resumes from checkpoint
            "io.resident_callback": fail_prob(0.2, seed=seed + 22),
            # cadence saves run INSIDE the window replay — a fault here
            # must unwind through the io_callback boundary cleanly
            "checkpoint.save": fail_prob(0.05, seed=seed + 23),
            "checkpoint.load": fail_prob(0.10, seed=seed + 24),
            # the per-dispatch body site (one hit per resident run)
            "optimize.streamed.step": fail_prob(0.10, seed=seed + 25),
        }
        with inject_faults(resident_faults):
            res_result = res_sup.run((X, y), w0)
            summary["resident_hits"] = {
                k: fp.hits(k) for k in resident_faults}
            summary["resident_triggers"] = {
                k: fp.triggers(k) for k in resident_faults}
        deadline.check("resident chaos phase")
        assert res_result.completed, (
            f"resident soak did not complete: {res_result.status}")
        assert summary["resident_hits"]["io.resident_callback"] > 0, (
            "the resident window callback was never reached")
        np.testing.assert_array_equal(
            np.asarray(res_result.weights), w_res_ref,
            err_msg="resident chaos weights diverged from fault-free")
        np.testing.assert_array_equal(
            res_result.loss_history, h_res_ref,
            err_msg="resident chaos loss history diverged")
        summary["resident_attempts"] = res_result.attempts
        say(f"resident driver survived: {res_result.attempts} "
            f"attempt(s), triggers={summary['resident_triggers']}, "
            "BITWISE equal to fault-free")

        # mid-run preempt -> boundary checkpoint -> resume, fault-free
        # wiring but the REAL preemption path: request_preempt from a
        # listener event firing inside the window replay; the stop
        # probe honors it at the NEXT cadence window boundary
        pre_dir = os.path.join(work, "ckpt_resident_pre")
        pre_opt = _make_resident_opt(iters)
        pre_sup = TrainingSupervisor(
            pre_opt, checkpoint_manager=CheckpointManager(pre_dir),
            checkpoint_every=100, install_signal_handlers=False)

        class _PreemptAt:
            def on_run_start(self, c): ...

            def on_iteration(self, ev):
                if ev.iteration == 5:
                    pre_sup.request_preempt()

            def on_run_end(self, ev): ...

        pre_opt.set_listener(_PreemptAt())
        pre_res = pre_sup.run((X, y), w0)
        window = 2 * 4  # cadence C=2 of K=4 supersteps
        assert pre_res.status == "preempted", pre_res.status
        assert pre_res.preempted_at % window == 0, (
            f"preempt landed off the cadence-window grid: "
            f"{pre_res.preempted_at}")
        pre_opt.set_listener(None)
        pre_res2 = pre_sup.run((X, y), w0)
        assert pre_res2.completed
        np.testing.assert_array_equal(
            np.asarray(pre_res2.weights), w_res_ref)
        np.testing.assert_array_equal(pre_res2.loss_history, h_res_ref)
        summary["resident_preempted_at"] = pre_res.preempted_at
        say(f"resident preempt at window boundary "
            f"{pre_res.preempted_at} + resume: bitwise equal")

        # torn-write corruption (deterministic, not seed-dependent):
        # truncate the newest TWO checkpoints mid-file and require the
        # restore fallback to quarantine both and land on the third
        torn = []
        km = CheckpointManager(
            kill_dir, on_corruption=lambda p, q, e: torn.append(q or p))
        victims = [km._path(v) for v in km.versions()[-2:]]
        for v in victims:
            with open(v, "r+b") as f:
                f.truncate(max(1, os.path.getsize(v) // 2))
        state = km.restore()
        assert state is not None and len(torn) == 2, (
            f"double-corrupt fallback failed ({len(torn)} quarantined)")
        summary["checkpoints_quarantined"] += len(torn)
        say(f"double-corrupt restore fell back to iteration "
            f"{state['iteration']}, quarantined {len(torn)} files")

        # ---- phase 1c: SPARSE compressed wire under fire -----------------
        # the host-streamed BCOO feed's compress/stage site
        # (io.sparse_wire, fired per staged batch inside the prefetch
        # retry scope) heals through the ingest RetryPolicy: the staged
        # batch is deterministic in (seed, i), so a healed re-stage is
        # identical and the faulted run must stay BITWISE equal to the
        # fault-free sparse run
        from tpu_sgd.ops.gradients import HingeGradient
        from tpu_sgd.ops.sparse import sparse_data

        deadline = Deadline(180.0)
        Xs, ys_lab, _ = sparse_data(384, 256, nnz_per_row=8, kind="svm",
                                    seed=seed)
        ws0 = np.zeros(Xs.shape[1], np.float32)

        def _make_sparse_opt(retry=None):
            from tpu_sgd.optimize.gradient_descent import GradientDescent

            o = (GradientDescent(gradient=HingeGradient())
                 .set_num_iterations(16).set_step_size(0.2)
                 .set_mini_batch_fraction(0.4).set_convergence_tol(0.0)
                 .set_seed(7).set_host_streaming(True).set_superstep(4))
            if retry is not None:
                o.set_ingest_options(retry=retry)
            return o

        w_sp_ref, h_sp_ref = _make_sparse_opt().optimize_with_history(
            (Xs, ys_lab), ws0)
        sparse_faults = {
            "io.sparse_wire": fail_prob(0.15, seed=seed + 30),
            "io.prefetch.produce": inject_latency(2.0, prob=0.2,
                                                  seed=seed + 31),
        }
        sp_opt = _make_sparse_opt(
            retry=RetryPolicy(max_attempts=6, base_backoff_s=0.002,
                              seed=seed + 32))
        with inject_faults(sparse_faults):
            w_sp, h_sp = sp_opt.optimize_with_history((Xs, ys_lab), ws0)
            summary["sparse_hits"] = {
                k: fp.hits(k) for k in sparse_faults}
            summary["sparse_triggers"] = {
                k: fp.triggers(k) for k in sparse_faults}
        deadline.check("sparse wire chaos phase")
        assert summary["sparse_hits"]["io.sparse_wire"] > 0, (
            "the sparse-wire stage site was never reached")
        np.testing.assert_array_equal(
            np.asarray(w_sp), np.asarray(w_sp_ref),
            err_msg="sparse chaos weights diverged from fault-free")
        np.testing.assert_array_equal(
            h_sp, h_sp_ref, err_msg="sparse chaos loss history diverged")
        say(f"sparse wire survived: triggers="
            f"{summary['sparse_triggers']}, BITWISE equal to fault-free")

        # ---- phase 1d: ASYNC REPLICA fleet under fire --------------------
        # the bounded-staleness subsystem (tpu_sgd/replica): transient
        # pull/push faults heal in place under the worker RetryPolicy
        # (τ=0: BITWISE vs fault-free — the protocol mutates nothing
        # before its failpoints), and a worker KILLED mid-run
        # deregisters (no fleet stall), rejoins with backoff, and the
        # run still lands at the synchronous final loss with the
        # staleness bound intact (asserted from the store snapshot
        # here, and from the replica.push trace events in phase 3)
        from tpu_sgd.replica import ReplicaDriver

        deadline = Deadline(300.0)
        rep_iters = max(24, iters)

        def _make_replica(tau, retry=None, rejoin_seed=None,
                          iters=None):
            drv = (ReplicaDriver()
                   .set_num_iterations(iters if iters is not None
                                       else rep_iters).set_step_size(0.1)
                   .set_mini_batch_fraction(1.0)
                   .set_convergence_tol(0.0).set_reg_param(0.01)
                   .set_seed(7).set_workers(4).set_staleness(tau))
            if retry is not None:
                drv.set_retry(retry)
            if rejoin_seed is not None:
                drv.set_rejoin(RetryPolicy(max_attempts=5,
                                           base_backoff_s=0.005,
                                           seed=rejoin_seed))
            return drv

        w_rep_ref, h_rep_ref = _make_replica(0).optimize_with_history(
            (X, y), w0)
        w_rep_ref = np.asarray(w_rep_ref)
        replica_faults = {
            "replica.pull": fp.fail_prob(0.05, seed=seed + 40),
            "replica.push": fp.fail_prob(0.05, seed=seed + 41),
        }
        heal_drv = _make_replica(
            0, retry=RetryPolicy(max_attempts=6, base_backoff_s=0.002,
                                 seed=seed + 42))
        with inject_faults(replica_faults):
            w_rh, h_rh = heal_drv.optimize_with_history((X, y), w0)
            summary["replica_hits"] = {
                k: fp.hits(k) for k in replica_faults}
            summary["replica_triggers"] = {
                k: fp.triggers(k) for k in replica_faults}
        deadline.check("replica heal chaos phase")
        assert all(n > 0 for n in summary["replica_hits"].values()), (
            "replica hook sites never reached")
        np.testing.assert_array_equal(
            np.asarray(w_rh), w_rep_ref,
            err_msg="healed replica τ=0 weights diverged from fault-free")
        np.testing.assert_array_equal(
            h_rh, h_rep_ref,
            err_msg="healed replica τ=0 loss history diverged")
        say(f"replica τ=0 fleet healed pull/push faults BITWISE, "
            f"triggers={summary['replica_triggers']}")

        # kill + rejoin mid-run at τ=2: the staleness bound must hold
        # and the final full-batch objective must match sync within 1%
        def _objective(wv):
            r = X @ np.asarray(wv) - y
            return float(0.5 * np.mean(r * r)
                         + 0.5 * 0.01 * np.sum(np.asarray(wv) ** 2))

        # aim the one-shot kill mid-run: pushes ~= applied versions at
        # τ>=1 (each accepted push IS one version), so hit N/2 lands in
        # the middle of the sweep.  The kill cell runs 4x the budget:
        # the rejoin is a RACE against the surviving workers' remaining
        # work (death detection + seeded backoff ≈ tens of ms, and a
        # fleet that finishes first never rejoins), so the post-kill
        # runway must dwarf that window or this phase flakes under load
        kill_iters = 4 * rep_iters
        kill_drv = _make_replica(2, rejoin_seed=seed + 43,
                                 iters=kill_iters)
        with inject_faults(
                {"replica.push": fp.fail_nth(rep_iters // 2)}):
            w_rk, h_rk = kill_drv.optimize_with_history((X, y), w0)
        deadline.check("replica kill/rejoin chaos phase")
        snap = kill_drv.last_store_snapshot
        members = kill_drv.last_membership_snapshot
        assert snap["version"] == kill_iters, snap
        assert snap["max_accepted_staleness"] <= 2, snap
        assert any(m["joins"] > 1 for m in members.values()), (
            f"no replica worker ever rejoined: {members}")
        obj_ref = _objective(w_rep_ref)
        obj_kill = _objective(w_rk)
        assert obj_kill <= obj_ref * 1.01, (
            f"kill/rejoin objective {obj_kill} vs sync {obj_ref}")
        summary["replica_kill"] = {
            "rejoins": sum(max(0, m["joins"] - 1)
                           for m in members.values()),
            "max_accepted_staleness": snap["max_accepted_staleness"],
            "pushes_rejected": snap["pushes_rejected"],
            "objective_ratio_vs_sync": obj_kill / obj_ref,
        }
        say(f"replica kill/rejoin at τ=2 survived: "
            f"{summary['replica_kill']}")

        # ---- phase 1e: straggler DETECTOR validation (ISSUE 13) ----------
        # the live-metrics twin of 1d: a dedicated kill cell tuned so
        # the victim's silence SPANS detector windows — the rejoin
        # backoff is long and DETERMINISTIC (jitter=0: the dead period
        # must cover >= 2 of the 0.25s windows every run, not most
        # runs) while the survivors keep stepping, and the budget gives
        # them enough runway that the rejoin still lands before the run
        # ends.  The replica-straggler rule must trip (a typed
        # obs_alert on the trace + the obs.alert counter), and the
        # driver's live `windows` snapshot must show the per-worker
        # replica.step series the rule evaluated.
        if trace_path is not None:
            deadline = Deadline(300.0)
            strag_drv = (_make_replica(2, iters=1200)
                         .set_rejoin(RetryPolicy(max_attempts=5,
                                                 base_backoff_s=0.8,
                                                 jitter=0.0,
                                                 seed=seed + 50)))
            with inject_faults({"replica.push": fp.fail_nth(24)}):
                strag_drv.optimize_with_history((X, y), w0)
            deadline.check("straggler detector phase")
            obs.flush_windows()  # the trailing window evaluates too
            strag_members = strag_drv.last_membership_snapshot
            assert any(m["joins"] > 1 for m in strag_members.values()), (
                f"straggler cell: victim never rejoined: {strag_members}")
            strag_trips = obs.snapshot().get(
                "obs.alert.replica-straggler", {"n": 0})["n"]
            assert strag_trips >= 1, (
                "the kill left a worker silent for >= 2 windows while "
                "the fleet ran, but the straggler detector never "
                "tripped")
            wins = strag_drv.last_windows_snapshot
            assert wins and any(
                name.startswith("replica.step[")
                for w in wins for name in w["series"]), (
                "driver windows snapshot carries no per-worker series")
            summary["straggler_detector"] = {
                "alerts": strag_trips,
                "rejoins": sum(max(0, m["joins"] - 1)
                               for m in strag_members.values()),
                "windows": len(wins),
            }
            say(f"straggler detector tripped {strag_trips} time(s) "
                f"across {len(wins)} live windows; victim rejoined")

        # ---- phase 1f: HA store failover (ISSUE 14) ----------------------
        # the availability layer under fire: (a) τ=0 with ONE standby
        # and the primary store KILLED mid-round (the replica.store_fail
        # failpoint raising StoreFailed at a store access) must be
        # BITWISE the fault-free τ=0 run — failover is a replay, not a
        # restart; (b) τ=2 with compressed pushes, one worker
        # PARTITIONED through a full failover (partition → primary kill
        # → heal, all while the fleet runs) must complete every version
        # with the staleness bound intact, fenced stale-epoch pushes
        # counted, and a matched objective — the partition is just a
        # longer rejection, zero EF mass lost.  Both cells run the
        # SHARDED store (2 apply pipelines): the bitwise and SLO pins
        # must survive per-shard delta-log payload groups too
        from tpu_sgd.replica import StoreFailed

        def _store_totals(snap):
            """``(fenced, replayed)`` for a possibly-sharded store
            snapshot: replay work is counted PER SHARD in the sharded
            spelling (``shard_replays`` — sum the list), while fencing
            happens at admission BEFORE shard routing, so
            ``pushes_fenced`` is a global scalar in both spellings."""
            replays = sum(snap.get("shard_replays", [0]))
            return int(snap["pushes_fenced"]), int(replays)

        deadline = Deadline(300.0)
        ha_drv = _make_replica(0).set_standbys(1).set_store_shards(2)
        # ~8 store accesses per τ=0 version (4 pulls + 4 pushes): the
        # one-shot kill at 4*rep_iters lands mid-run (~version N/2)
        with inject_faults({"replica.store_fail": fp.fail_nth(
                4 * rep_iters, exc=StoreFailed)}):
            w_ha, h_ha = ha_drv.optimize_with_history((X, y), w0)
        deadline.check("HA store-kill chaos phase")
        ha_snap = ha_drv.last_failover_snapshot
        assert ha_snap["failovers"] == 1, ha_snap
        np.testing.assert_array_equal(
            np.asarray(w_ha), w_rep_ref,
            err_msg="τ=0 weights diverged across the store failover")
        np.testing.assert_array_equal(
            h_ha, h_rep_ref,
            err_msg="τ=0 loss history diverged across the store failover")
        ha_store_snap = ha_drv.last_store_snapshot
        assert ha_store_snap["store_shards"] == 2, ha_store_snap
        _, ha_replayed = _store_totals(ha_store_snap)
        assert ha_replayed >= 1, (
            "the promoted sharded store never replayed a per-shard "
            f"payload group: {ha_store_snap}")
        summary["store_failover"] = dict(
            ha_snap["records"][0],
            shard_replays=ha_store_snap["shard_replays"])
        say(f"store failover at τ=0 BITWISE (sharded store): "
            f"{summary['store_failover']}")

        # (b) partition one worker THROUGH the failover
        deadline = Deadline(300.0)
        part_iters = 8 * rep_iters
        part_drv = (_make_replica(
            2, retry=RetryPolicy(max_attempts=400, base_backoff_s=0.01,
                                 max_backoff_s=0.05, seed=seed + 70),
            iters=part_iters)
            .set_standbys(1).set_wire_compress("topk:0.25")
            .set_store_shards(2))
        import threading as _threading

        timers = [
            _threading.Timer(0.25, part_drv.partition_worker, ("w1",)),
            _threading.Timer(0.6, part_drv.kill_primary),
            _threading.Timer(1.2, part_drv.heal_worker, ("w1",)),
        ]
        for t in timers:
            t.start()
        try:
            w_pt, h_pt = part_drv.optimize_with_history((X, y), w0)
        finally:
            for t in timers:
                t.cancel()
        deadline.check("HA partition chaos phase")
        pt_snap = part_drv.last_store_snapshot
        assert part_drv.last_failover_snapshot["failovers"] == 1, (
            part_drv.last_failover_snapshot)
        assert pt_snap["version"] == part_iters, pt_snap
        assert pt_snap["max_accepted_staleness"] <= 2, pt_snap
        pt_fenced, pt_replayed = _store_totals(pt_snap)
        assert pt_fenced >= 1, (
            "no push was ever epoch-fenced across the failover")
        assert pt_replayed >= 1, (
            "the promoted sharded store replayed no compressed "
            f"per-shard payload group: {pt_snap}")
        obj_pt = _objective(w_pt)
        assert obj_pt <= _objective(w_rep_ref) * 1.01, (
            f"partitioned-through-failover objective {obj_pt}")
        summary["store_partition"] = {
            "failovers": part_drv.last_failover_snapshot["failovers"],
            "pushes_fenced": pt_fenced,
            "pushes_rejected": pt_snap["pushes_rejected"],
            "shard_replays": pt_snap["shard_replays"],
            "objective_ratio_vs_sync": obj_pt / _objective(w_rep_ref),
        }
        say(f"partition through failover survived: "
            f"{summary['store_partition']}")
        if trace_path is not None:
            obs.flush_windows()
            fo_trips = obs.snapshot().get(
                "obs.alert.failover", {"n": 0})["n"]
            assert fo_trips >= 1, (
                "two store promotions ran but the failover detector "
                "never tripped")
            summary["failover_alerts"] = fo_trips
            say(f"failover detector tripped {fo_trips} time(s)")

        # ---- phase 1g: END-TO-END DATA INTEGRITY (ISSUE 15) --------------
        # the corrupting failpoint mode armed at every checksummed
        # wire: a corrupt_prob spec silently MUTATES payload copies
        # (bit flips, NaNs, truncations) exactly where real wire/DMA/
        # storage damage would land, the consume-site verify turns each
        # into a typed IntegrityError, the existing retry machinery
        # heals it, and the healed runs are BITWISE the fault-free
        # references this soak already computed.  Then the two poison
        # cells: checksums OFF so NaN corruption reaches the store's
        # numerical admission gate (poisoned pushes, matched loss), and
        # the forced weight-corruption rollback (failover to your own
        # past through epoch fencing).
        from tpu_sgd.io.integrity import set_integrity

        deadline = Deadline(300.0)
        # (a) dense chunks + superchunks: corrupt_prob at io.chunk
        chunk_opt = _make_opt(
            iters, "sliced",
            retry=RetryPolicy(max_attempts=6, base_backoff_s=0.002,
                              seed=seed + 80))
        with inject_faults({"io.chunk": fp.corrupt_prob(
                0.5, seed=seed + 81)}):
            w_ci, h_ci = chunk_opt.optimize_with_history((X, y), w0)
            chunk_triggers = fp.triggers("io.chunk")
        assert chunk_triggers > 0, "io.chunk corruption never fired"
        np.testing.assert_array_equal(
            np.asarray(w_ci), w_ref,
            err_msg="corrupt-chunk healed run diverged from fault-free")
        np.testing.assert_array_equal(h_ci, h_ref)

        # (b) sparse chunks: corrupt_prob (truncation) at io.sparse_chunk
        sp_opt2 = _make_sparse_opt(
            retry=RetryPolicy(max_attempts=6, base_backoff_s=0.002,
                              seed=seed + 82))
        with inject_faults({"io.sparse_chunk": fp.corrupt_prob(
                0.5, seed=seed + 83, kind="truncate")}):
            w_sci, h_sci = sp_opt2.optimize_with_history((Xs, ys_lab),
                                                         ws0)
            sparse_triggers = fp.triggers("io.sparse_chunk")
        assert sparse_triggers > 0
        np.testing.assert_array_equal(np.asarray(w_sci),
                                      np.asarray(w_sp_ref))
        np.testing.assert_array_equal(h_sci, h_sp_ref)

        # (c) push payloads + EF segments + delta-log records: a τ=0
        # fleet with one standby, corruption armed on all three wires
        # at once — pushes heal under the worker RetryPolicy, segments
        # at the extraction boundary, log records by re-reading the
        # intact retained record; bitwise vs the fault-free τ=0 run
        wire_drv = (_make_replica(
            0, retry=RetryPolicy(max_attempts=8, base_backoff_s=0.002,
                                 seed=seed + 84)).set_standbys(1))
        wire_faults = {
            "replica.push.wire": fp.corrupt_prob(0.05, seed=seed + 85),
            "replica.log.record": fp.corrupt_prob(0.2, seed=seed + 86,
                                                  kind="nan"),
        }
        with inject_faults(wire_faults):
            w_wi, h_wi = wire_drv.optimize_with_history((X, y), w0)
            wire_triggers = {k: fp.triggers(k) for k in wire_faults}
        assert all(n > 0 for n in wire_triggers.values()), wire_triggers
        np.testing.assert_array_equal(
            np.asarray(w_wi), w_rep_ref,
            err_msg="corrupt-wire healed replica run diverged")
        np.testing.assert_array_equal(h_wi, h_rep_ref)

        # (d) POISON ADMISSION: checksums off — NaN corruption now
        # reaches the store's numerical gate, which rejects the pushes
        # WHOLE (typed poisoned); the workers recompute from (seed,
        # version) and the run lands at the matched objective.  The
        # store is SHARDED: the gate runs at the push consume site,
        # before shard routing, so a poisoned push never reaches any
        # pipeline — the per-shard push counts stay equal (dense
        # pushes touch every shard) even while poison rejects
        set_integrity(False)
        try:
            poison_drv = _make_replica(2, iters=2 * rep_iters)
            poison_drv.set_store_shards(2)
            with inject_faults({"replica.push.wire": fp.corrupt_prob(
                    0.08, seed=seed + 87, kind="nan")}):
                w_po, _ = poison_drv.optimize_with_history((X, y), w0)
        finally:
            set_integrity(True)
        po_snap = poison_drv.last_store_snapshot
        assert po_snap["pushes_poisoned"] >= 1, po_snap
        assert po_snap["version"] == 2 * rep_iters, po_snap
        assert po_snap["store_shards"] == 2, po_snap
        assert len(set(po_snap["shard_pushes"])) == 1, (
            f"poison admission skewed the shard routing: {po_snap}")
        obj_po = _objective(w_po)
        assert obj_po <= _objective(w_rep_ref) * 1.01, obj_po

        # (e) CORRUPT-STATE ROLLBACK: poison planted in the live
        # primary's weights (past any gate) — the armed controller
        # fences the poisoned epoch, restores the last good checkpoint,
        # and the τ=0 replay is BITWISE the clean run
        import threading as _rb_threading

        rb_dir = os.path.join(work, "rollback_ckpt")
        rb_clean_dir = os.path.join(work, "rollback_clean")
        rb_iters = 2 * rep_iters
        rb_ref = _make_replica(0, iters=rb_iters)
        rb_ref.set_checkpoint(CheckpointManager(rb_clean_dir, keep=4),
                              every=5)
        w_rb_ref, h_rb_ref = rb_ref.optimize_with_history((X, y), w0)
        rb_drv = _make_replica(0, iters=rb_iters)
        rb_drv.set_checkpoint(CheckpointManager(rb_dir, keep=4),
                              every=5).set_integrity_rollback(True)

        def _corrupter():
            import time as _t

            end = _t.monotonic() + 120
            while _t.monotonic() < end:
                sup = rb_drv._live_supervisor
                if sup is not None:
                    try:
                        if sup.primary().version >= rb_iters // 3:
                            rb_drv.chaos_corrupt_weights()
                            return
                    except Exception:
                        pass
                _t.sleep(0.002)

        rb_t = _rb_threading.Thread(target=_corrupter, daemon=True)
        rb_t.start()
        w_rb, h_rb = rb_drv.optimize_with_history((X, y), w0)
        rb_t.join(timeout=10)
        rb_snap = rb_drv.last_failover_snapshot
        assert rb_snap is not None and rb_snap["failovers"] >= 1, rb_snap
        assert any(r["cold_recovery"] for r in rb_snap["records"])
        np.testing.assert_array_equal(
            np.asarray(w_rb), np.asarray(w_rb_ref),
            err_msg="rollback replay diverged from the clean run")
        np.testing.assert_array_equal(h_rb, h_rb_ref)
        deadline.check("integrity phase")
        summary["integrity"] = {
            "chunk_corruptions_healed": chunk_triggers,
            "sparse_corruptions_healed": sparse_triggers,
            "wire_corruptions_healed": wire_triggers,
            "pushes_poisoned": po_snap["pushes_poisoned"],
            "poison_objective_ratio": obj_po / _objective(w_rep_ref),
            "rollbacks": rb_snap["failovers"],
            "rollback_epoch": rb_drv.last_store_snapshot["epoch"],
        }
        say(f"integrity: every corrupted wire healed BITWISE, "
            f"{po_snap['pushes_poisoned']} poisoned pushes rejected, "
            f"weight-corruption rolled back bitwise: "
            f"{summary['integrity']}")
        if trace_path is not None:
            obs.flush_windows()
            integ_trips = obs.snapshot().get(
                "obs.alert.integrity", {"n": 0})["n"]
            assert integ_trips >= 1, (
                "corrupt frames were detected at every wire but the "
                "integrity detector never tripped")
            summary["integrity"]["alerts"] = integ_trips

        # ---- phase 2: serving under reload faults ------------------------
        deadline = Deadline(120.0)
        breaker = CircuitBreaker(failure_threshold=3, reset_timeout_s=0.05)
        registry = ModelRegistry(
            manager, lambda w, b: LinearRegressionModel(w, b),
            breaker=breaker)
        # an injected reload fault on the newest version legitimately
        # rolls serving back to an OLDER retained checkpoint, so the
        # no-corruption invariant is: every answer is bitwise the
        # prediction of SOME intact retained version — never a value no
        # healthy model would produce
        Xq = X[:64]
        want_by_version = {
            v: np.asarray(LinearRegressionModel(
                manager.restore_version(v)["weights"], 0.0).predict(Xq))
            for v in manager.versions()
        }
        want = want_by_version[iters]  # the final-weights answers
        serve_faults = {
            "serve.registry.reload": fail_prob(0.4, seed=seed + 6),
            "serve.batcher.enqueue": fail_prob(0.05, seed=seed + 7),
        }
        answered = rejected = 0
        with inject_faults(serve_faults):
            with Server(registry=registry, max_latency_s=0.002,
                        event_log=event_log,
                        reload_interval_s=0.0) as server:
                monitor = HealthMonitor(listener=event_log,
                                        stall_after_s=30.0)
                monitor.watch_heartbeat(server.batcher.heartbeat)
                monitor.watch_queue("serve.batcher",
                                    lambda: server.batcher.queue_depth)
                futs = []
                for i in range(Xq.shape[0]):
                    deadline.check("serve submit loop")
                    try:
                        futs.append((i, server.submit(Xq[i])))
                    except fp.FaultInjected:
                        rejected += 1  # admission fault: shed, not hung
                for i, f in futs:
                    got = np.asarray(f.result(timeout=30))  # no-hang bound
                    assert any(got == w[i]
                               for w in want_by_version.values()), (
                        f"row {i}: served {got}, which no retained "
                        f"version produces (final: {want[i]})")
                    answered += 1
                health = server.healthz()
                monitor.sample_once()
            summary["serve_reload_triggers"] = fp.triggers(
                "serve.registry.reload")
            assert all(fp.hits(k) > 0 for k in serve_faults), (
                "serve hook sites never reached")
        deadline.check("serving phase")
        assert answered + rejected == Xq.shape[0]
        assert answered > 0, "every request was rejected"
        # healthz consistency: whatever version answered must be a real
        # retained version, and the breaker snapshot must be well-formed
        assert health["model_version"] in manager.versions()
        assert health["registry"]["breaker"]["state"] in (
            "closed", "open", "half_open")
        summary["served"] = answered
        summary["shed"] = rejected
        summary["breaker"] = health["registry"]["breaker"]
        say(f"serving: {answered} answered correctly, {rejected} shed "
            f"by injected admission faults, breaker={summary['breaker']}")

        # ---- phase 2b: overload burst with serve.admit armed -------------
        # admission control under fire: a 300-request burst drowns a
        # deliberately tiny endpoint (16-deep queue, 8-row batches)
        # across all three priority lanes while the serve.admit
        # failpoint (which fires BEFORE any queue mutation, so a healed
        # retry replays nothing twice) randomly rejects arrivals.  The
        # invariant is the typed-rejection ledger: every one of the 300
        # submissions is answered, typed-Overloaded (shed / queue_full /
        # displaced), or FaultInjected — no hangs, no silent drops.
        from tpu_sgd.serve import Overloaded

        deadline = Deadline(120.0)
        burst_faults = {"serve.admit": fail_prob(0.2, seed=seed + 8)}
        b_answered = b_overloaded = b_faulted = 0
        burst_n = 300
        lanes_cycle = ("interactive", "interactive", "batch", "shadow")
        with inject_faults(burst_faults):
            with Server(LinearRegressionModel(w_ref, 0.0), max_batch=8,
                        max_latency_s=0.001, max_queue=16,
                        event_log=event_log) as bsrv:
                bfuts = []
                for i in range(burst_n):
                    deadline.check("overload burst submit loop")
                    lane = lanes_cycle[i % len(lanes_cycle)]
                    try:
                        bfuts.append(bsrv.submit(
                            Xq[i % Xq.shape[0]], lane=lane,
                            deadline_s=(0.25 if lane == "interactive"
                                        else None)))
                    except fp.FaultInjected:
                        b_faulted += 1  # injected admission fault: typed
                    except Overloaded as e:
                        assert e.reason in ("queue_full", "deadline",
                                            "shed"), e.reason
                        b_overloaded += 1
                for f in bfuts:
                    try:
                        got = np.asarray(f.result(timeout=30))  # no-hang
                        assert np.all(np.isfinite(got))
                        b_answered += 1
                    except Overloaded as e:  # displaced: typed answer
                        assert e.reason == "displaced", e.reason
                        b_overloaded += 1
                burst_health = bsrv.healthz()
            assert fp.hits("serve.admit") > 0, (
                "the serve.admit hook site was never reached")
        deadline.check("overload burst phase")
        assert b_answered + b_overloaded + b_faulted == burst_n, (
            f"burst ledger does not conserve: {b_answered} answered + "
            f"{b_overloaded} typed + {b_faulted} faulted != {burst_n}")
        assert b_answered > 0, "the burst answered nothing"
        assert b_overloaded > 0, (
            "a 300-request burst at a 16-deep queue shed nothing — "
            "admission control never engaged")
        lane_counts = burst_health["lanes"]
        assert burst_health["shed_count"] + burst_health["reject_count"] > 0
        summary["burst"] = {
            "answered": b_answered, "typed_rejections": b_overloaded,
            "admission_faults": b_faulted,
            "lanes": {k: {kk: vv for kk, vv in v.items() if kk != "depth"}
                      for k, v in lane_counts.items()},
        }
        say(f"overload burst: {b_answered} answered, {b_overloaded} "
            f"typed rejections, {b_faulted} injected admission faults "
            f"— ledger conserved, no hangs")

        # the burst must have TRIPPED the shed-rate detector (ISSUE 13):
        # per-lane typed-rejection rate over the windowed admission
        # counters, evaluated live at window close — the alert is a
        # typed obs_alert on this soak's trace (the SLO gate re-asserts
        # it offline) and the flight recorder dumped on the transition
        if trace_path is not None:
            obs.flush_windows()
            shed_trips = obs.snapshot().get(
                "obs.alert.shed-rate", {"n": 0})["n"]
            assert shed_trips >= 1, (
                "a 300-request burst at a 16-deep queue shed heavily "
                "but the shed-rate detector never tripped")
            summary["shed_rate_alerts"] = shed_trips
            say(f"shed-rate detector tripped {shed_trips} time(s) "
                "under the burst")

        # ---- phase 3: event log survives a torn tail ---------------------
        if trace_path is not None:
            # flushes the cumulative counter snapshot as the trace's
            # final metric_counters record, unwinds the runtime
            # patches, and drops the sink ref (caller-owned log: the
            # close below is ours)
            obs.disable()
        event_log.close()
        with open(log_path, "a") as f:
            f.write('{"kind": "torn_mid_rec')  # simulated crash tail
        events = JsonLinesEventLog.read(log_path)
        kinds = {e["kind"] for e in events}
        assert any(k.startswith("reliability_") for k in kinds), (
            f"no reliability_* events logged (got {sorted(kinds)})")
        assert not any("torn" in k for k in kinds)
        summary["events_logged"] = len(events)
        say(f"event log: {len(events)} events replayed past the torn tail")

        if trace_path is not None:
            # the replica staleness bound, asserted from the TRACE
            # itself (every replica.push trace_event carries the
            # staleness its application observed), not just the store's
            # own counters: phase 1d ran τ=0 and τ=2 fleets, so no
            # accepted push anywhere in this soak may exceed 2
            pushes = [e for e in events
                      if e.get("kind") == "trace_event"
                      and e.get("name") == "replica.push"]
            accepted = [e for e in pushes if e.get("accepted")]
            assert accepted, "no replica.push events in the trace"
            worst = max(e["staleness"] for e in accepted)
            assert worst <= 2, (
                f"trace shows an accepted push {worst} versions stale")
            summary["replica_trace_pushes"] = len(pushes)
            summary["replica_trace_max_accepted_staleness"] = worst
            say(f"replica staleness bound held in the trace: "
                f"{len(accepted)} accepted pushes, worst {worst}")

            # the flight recorder's standalone dump (the detector trips
            # above triggered it) schema-validates: a meta header, the
            # ring of real trace records, and the windowed snapshots a
            # post-mortem renders without replaying the full trace
            frec = JsonLinesEventLog.read(flight_path)
            assert frec and frec[0]["kind"] == "flightrec_meta", (
                f"flight record at {flight_path} missing its meta "
                "header")
            frec_kinds = {r["kind"] for r in frec}
            assert "obs_window" in frec_kinds, (
                f"flight record carries no window snapshots: "
                f"{sorted(frec_kinds)}")
            assert frec_kinds & {"trace_span", "trace_event",
                                 "obs_alert"}, (
                f"flight record ring is empty of trace records: "
                f"{sorted(frec_kinds)}")
            summary["flightrec"] = {
                "path": flight_path,
                "records": len(frec),
                "reason": frec[0]["reason"],
                "dumps": frec[0]["dump_ordinal"],
            }
            say(f"flight record validated: {len(frec)} records, "
                f"last trigger {frec[0]['reason']!r} "
                f"(dump #{frec[0]['dump_ordinal']})")

    summary["ok"] = True
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--iters", type=int, default=40)
    ap.add_argument("--quiet", action="store_true")
    ap.add_argument("--trace", metavar="OUT.jsonl",
                    default="chaos_trace.jsonl",
                    help="soak trace path (the soak's event log; "
                         "default %(default)s); --trace '' disables")
    ap.add_argument("--slo", metavar="SLO.json", default=None,
                    help="SLO file for the post-soak report (default: "
                         "the built-in structural assertions)")
    ap.add_argument("--chrome", metavar="OUT.json", default=None,
                    help="Chrome trace-event export path (default: "
                         "<trace>.chrome.json)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.ERROR)  # chaos warnings are expected
    trace = args.trace or None
    try:
        summary = soak(seed=args.seed, iters=args.iters,
                       verbose=not args.quiet, trace_path=trace)
    finally:
        # a failed invariant must not leave the runtime patches or the
        # closed log's sink ref behind (idempotent when trace is off)
        from tpu_sgd import obs

        obs.disable()
    print(json.dumps(summary, indent=2, default=str))
    if trace is None:
        return 0

    # ---- the report pipeline over the soak's own trace -------------------
    # (torn tail and all: phase 3 tore the final line on purpose, and
    # obs.report parses past it via the shared read() semantics)
    from tpu_sgd.obs import report as obs_report

    slo_path = args.slo
    if slo_path is None:
        slo_path = trace + ".slo.json"
        with open(slo_path, "w") as f:
            json.dump(DEFAULT_SLOS, f, indent=2)
    chrome = args.chrome or (trace + ".chrome.json")
    # the report CLI's exit code IS this CLI's exit code from here on:
    # 0 = SLOs hold, 1 = violation, 2 = unreadable trace/SLO file
    return obs_report.main([trace, "--slo", slo_path,
                            "--chrome", chrome])


if __name__ == "__main__":
    sys.exit(main())
