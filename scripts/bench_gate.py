"""Bench regression gate: committed BENCH_*.json headline counts and
bytes-ratios become a CI gate instead of a file.

The 2-core harness policy (ROADMAP): wall clocks there are weather, so
the benches headline COUNTS (dispatches, syncs, compiles, admissions)
and BYTES-RATIOS (wire compression) — structural numbers that
reproduce exactly or near-exactly.  This gate pins them: every metric
in :data:`GATES` is compared candidate-vs-baseline with a declared
tolerance band in the metric's GOOD direction (an improvement always
passes; only a regression beyond the band fails).  Wall-clock fields
are deliberately ungated.

Modes::

    python scripts/bench_gate.py
        # self-check: baseline == candidate == the repo's committed
        # files.  Verifies every gated metric EXISTS and parses —
        # schema drift (a vanished headline number) fails here, and a
        # freshly committed BENCH file is validated at commit time.

    python scripts/bench_gate.py --candidate-dir /tmp/fresh
        # the real comparison: freshly produced BENCH files (a local
        # bench re-run) against the committed baselines.  CI also runs
        # this against a deliberately perturbed copy and requires exit
        # 1 — a gate only ever seen passing is a gate nobody tested.

Exit codes (the ``obs.report`` contract): 0 = every gate holds, 1 = a
regression / missing candidate metric, 2 = unreadable baseline or
usage error.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import re
import sys
from typing import List

#: graftlint lock-discipline declaration (tpu_sgd/analysis): EMPTY on
#: purpose — a single-threaded offline comparator, no shared state.
GRAFTLINT_LOCKS: dict = {}


@dataclasses.dataclass(frozen=True)
class Gate:
    """One gated metric.

    ``path`` is a ``/``-separated JSON path (``arms/shed_off/
    levels[3]/x`` — list indices in brackets; ``/`` rather than ``.``
    because bench keys like ``d47236_topk0.01`` contain dots).
    ``better`` declares the good
    direction: ``"higher"`` (ratios, throughput counts) fails when the
    candidate drops more than the band below baseline; ``"lower"``
    (dispatch/sync/compile counts) fails when it rises more than the
    band above; ``"equal"`` (structural counts like spans-per-run)
    fails on ANY deviation beyond the band either way.  The band is
    ``rel_tol * |baseline| + abs_tol``."""

    path: str
    better: str  # "higher" | "lower" | "equal"
    rel_tol: float = 0.0
    abs_tol: float = 0.0
    note: str = ""


#: the declared tolerance bands — counts/ratios only, per the 2-core
#: policy (walls are weather; they stay in the files as context, not
#: gates).
GATES = {
    "BENCH_OBS.json": [
        # the PR 8 acceptance pin, as numbers: enabled obs adds ZERO
        # runtime events on the warmed drivers — any nonzero delta is
        # a regression with no noise excuse
        Gate("headline/superstep_count_deltas/dispatches", "lower",
             note="enabled-minus-disabled must stay 0"),
        Gate("headline/superstep_count_deltas/host_syncs", "lower"),
        Gate("headline/superstep_count_deltas/compiles", "lower"),
        Gate("headline/resident_count_deltas/dispatches", "lower"),
        Gate("headline/resident_count_deltas/host_syncs", "lower"),
        Gate("headline/resident_count_deltas/compiles", "lower"),
        # structural per-run counts on the warmed drivers: exact by
        # construction; a small band absorbs a deliberate driver
        # change landing with a refreshed baseline
        Gate("detail/superstep/counts_enabled/dispatches", "lower",
             rel_tol=0.05),
        Gate("detail/resident/counts_enabled/dispatches", "lower",
             rel_tol=0.05),
        Gate("detail/superstep/trace_spans_per_run", "equal",
             note="span inventory drift = instrumentation regression"),
        Gate("detail/resident/trace_spans_per_run", "equal"),
    ],
    "BENCH_SERVE.json": [
        # admission ledgers over the fixed offered schedule: total
        # admitted ~ sustained throughput as a COUNT; the wide band is
        # the 2-core load-timing noise, a collapse still fails
        Gate("arms/shed_off/admission_counts/admit_count", "higher",
             rel_tol=0.5),
        Gate("arms/shed_on/admission_counts/admit_count", "higher",
             rel_tol=0.5),
        # coalescing at saturation: mean rows per flushed batch
        Gate("arms/shed_off/levels[3]/mean_batch_size", "higher",
             rel_tol=0.3, note="batcher stopped coalescing"),
        # -- the multi-tenant slab sweep (ISSUE 18): structural, exact --
        # scoring dispatches per mixed-tenant batch must be FLAT across
        # tenant counts (the shape-trap contract: tenant identity is a
        # traced index vector, never a program key) — 1.0 at every M
        Gate("tenant_sweep/cells[0]/dispatches_per_batch", "equal",
             note="M=16 mixed batch must stay one dispatch"),
        Gate("tenant_sweep/cells[1]/dispatches_per_batch", "equal",
             note="M=256 mixed batch must stay one dispatch"),
        Gate("tenant_sweep/cells[2]/dispatches_per_batch", "equal",
             note="M=2048 mixed batch must stay one dispatch — "
                  "dispatch count independent of tenant count"),
        # zero compiles after warm-up at ANY tenant count: a nonzero
        # delta means a shape escaped the slab's program-key discipline
        Gate("tenant_sweep/cells[0]/compiles_after_warm", "lower"),
        Gate("tenant_sweep/cells[2]/compiles_after_warm", "lower",
             note="tenant churn must never reach the XLA compiler"),
        # the Zipf head must keep hitting the slab: M=16 fits entirely
        # (rate ~1.0); M=2048 serves mostly from the resident head
        Gate("tenant_sweep/cells[0]/slab_hit_rate", "higher",
             rel_tol=0.02),
        Gate("tenant_sweep/cells[2]/slab_hit_rate", "higher",
             rel_tol=0.25,
             note="Zipf head stopped fitting the slab — LRU or "
                  "admission regression"),
        # burst admission must keep amortizing the lock: ~1/rows rounds
        # per row for a whole burst, exactly 1.0 per-request
        Gate("tenant_sweep/burst_admission/burst/rounds_per_row",
             "lower", rel_tol=0.5,
             note="vectorized burst admission stopped amortizing the "
                  "admission lock"),
        Gate("tenant_sweep/burst_admission/per_request/rounds_per_row",
             "equal",
             note="per-request admission is the 1.0 basis the burst "
                  "ratio is read against"),
    ],
    "BENCH_RESIDENT.json": [
        # the one-fused-dispatch contract (ISSUE 20): however many
        # iterations the run covers, the resident driver launches ONE
        # program — exact by construction, any extra launch is a
        # regression with no noise excuse
        Gate("counts/resident/optimize.streamed.step", "lower",
             note="the full resident run must stay ONE fused dispatch"),
        Gate("counts/dispatch_reduction_vs_superstep_x", "higher",
             rel_tol=0.05),
        Gate("counts/round_trip_reduction_vs_superstep_x", "higher",
             rel_tol=0.05),
        Gate("counts/h2d_bytes_reduction_vs_k1_x", "higher",
             rel_tol=0.05),
        # resident + EF (ISSUE 20): the error-feedback accumulator is a
        # while_loop carry leaf, so the composed run keeps the dense
        # cell's shape — one dispatch, >= 10x fewer than the compressed
        # superstep twin, bitwise trajectory
        Gate("ef_cell/resident/optimize.streamed.step", "lower",
             note="EF carry must keep the one-dispatch contract"),
        Gate("ef_cell/dispatch_reduction_vs_superstep_x", "higher",
             rel_tol=0.05,
             note="the ISSUE 20 >= 10x acceptance number"),
        Gate("ef_cell/bitwise_vs_compressed_superstep", "equal",
             note="resident+EF must replay the compressed superstep "
                  "trajectory bitwise — drift means the carried EF "
                  "diverged from the host accumulator"),
        # resident + sparse (ISSUE 20): the fixed-nse BCOO feed variant
        # of the same driver — runtime-twin dispatch counts, small band
        # for staging-op drift on a deliberate driver change
        Gate("sparse_cell/dispatches/resident", "lower", rel_tol=0.10),
        Gate("sparse_cell/dispatch_reduction_vs_superstep_x", "higher",
             rel_tol=0.10),
        Gate("sparse_cell/bitwise_vs_sparse_superstep", "equal",
             note="the sparse slab feed must stay bitwise its "
                  "superstep twin"),
    ],
    "BENCH_SPARSE_WIRE.json": [
        Gate("sparse_feed/wire_bytes/ratio", "higher", rel_tol=0.10,
             note="BCOO feed physical-vs-dense-f32 compression"),
        Gate("sparse_feed/counts/dispatches_per_run", "lower",
             rel_tol=0.05),
        Gate("topk_compress/d47236_topk0.01/ratio", "higher",
             rel_tol=0.05),
        Gate("topk_compress/d1000000_topk0.01/ratio", "higher",
             rel_tol=0.05),
        Gate("merge_wire/ratio", "higher", rel_tol=0.10),
    ],
    "BENCH_ASYNC.json": [
        # the HA failover cell (ISSUE 14): structural counts — a kill
        # must produce EXACTLY one promotion, and the τ=0 post-failover
        # trajectory must stay bitwise (1 = equal; any drift is a
        # correctness regression, not noise)
        Gate("failover/failovers", "equal",
             note="the store-kill cell must fail over exactly once"),
        Gate("failover/bitwise_vs_fault_free", "equal",
             note="τ=0 failover must replay, not fork — ADVICE.md "
                  "'Failover is a replay, not a restart'"),
        # the compressed failover twin's matched-objective bar: the
        # baseline sits well UNDER 1.0 (EF carry beats dense sync at
        # this config), the wide band absorbs τ>=1 interleaving noise,
        # and the bench's own <=1.01 assertion stays the hard ceiling
        Gate("failover/compressed/objective_ratio_vs_sync", "lower",
             rel_tol=0.25),
        # the store-shard sweep (tpu_sgd/replica/shard.py): structural
        # counts, exact by construction at τ=0 — every S accepts the
        # same ITERS*W pushes, each pipeline applies exactly ITERS
        # combines, and the sharded trajectory stays bitwise the
        # unsharded one (1 = equal; drift = a broken combine, never
        # noise)
        Gate("store_shard_sweep/cells[0]/pushes_accepted", "equal",
             note="S=1 cell: ITERS*W accepted pushes"),
        Gate("store_shard_sweep/cells[1]/pushes_accepted", "equal",
             note="S=2 cell accepts the same pushes as unsharded"),
        Gate("store_shard_sweep/cells[2]/pushes_accepted", "equal",
             note="S=4 cell accepts the same pushes as unsharded"),
        Gate("store_shard_sweep/cells[1]/bitwise_vs_unsharded",
             "equal", note="S=2 τ=0 trajectory must stay bitwise — "
                           "ADVICE.md 'Shard the apply, not the "
                           "contract'"),
        Gate("store_shard_sweep/cells[2]/bitwise_vs_unsharded",
             "equal", note="S=4 τ=0 trajectory must stay bitwise"),
        Gate("store_shard_sweep/cells[1]/shard_applies[0]", "equal",
             note="each pipeline applies exactly ITERS combines"),
        Gate("store_shard_sweep/cells[2]/shard_applies[3]", "equal",
             note="the last of 4 pipelines applies exactly ITERS "
                  "combines — a short list here means a pipeline "
                  "vanished"),
    ],
    "BENCH_INTEGRITY.json": [
        # the integrity plane's acceptance pin as numbers (ISSUE 15):
        # checksums are pure host work, so the warmed fused driver's
        # dispatch/sync counts must be IDENTICAL with the plane on vs
        # off — any nonzero delta is a regression with no noise excuse
        Gate("headline/zero_added_runtime/dispatch_delta", "lower",
             note="checksums-on must add zero dispatches"),
        Gate("headline/zero_added_runtime/host_sync_delta", "lower",
             note="checksums-on must add zero host syncs"),
        # structural: one seal+verify per superchunk — exact by
        # construction (24 iters / K=4 = 6 frames)
        Gate("headline/frames_verified_per_run", "equal",
             note="frame inventory drift = a wire lost its checksum"),
        # the wire-size price: payload bytes per 4-byte CRC; exact for
        # a fixed run shape, small band for a deliberate shape change
        Gate("headline/checksum_overhead_bytes_ratio", "higher",
             rel_tol=0.05),
    ],
}

_SEG = re.compile(r"^(?P<key>.*?)(?P<idx>(\[\d+\])*)$")


def lookup(doc, path: str):
    """Resolve a ``/``-separated path (``a/b[3]/c``); raises KeyError
    with the failing segment named."""
    cur = doc
    for seg in path.split("/"):
        m = _SEG.match(seg)
        key = m.group("key")
        try:
            if key:
                cur = cur[key]
            for idx in re.findall(r"\[(\d+)\]", m.group("idx")):
                cur = cur[int(idx)]
        except (KeyError, IndexError, TypeError):
            raise KeyError(f"{path!r}: missing segment {seg!r}")
    return cur


def check_gate(gate: Gate, baseline, candidate) -> dict:
    """One verdict dict: {path, better, baseline, candidate, ok,
    detail?} — the SLO-verdict shape, for the same reasons."""
    v = {"path": gate.path, "better": gate.better}
    try:
        b = float(lookup(baseline, gate.path))
    except (KeyError, ValueError, TypeError) as e:
        return {**v, "ok": False, "detail": f"baseline: {e}"}
    try:
        c = float(lookup(candidate, gate.path))
    except (KeyError, ValueError, TypeError) as e:
        # a vanished candidate metric IS a regression (the headline
        # number someone stopped measuring), never a skip
        return {**v, "baseline": b, "ok": False,
                "detail": f"candidate: {e}"}
    band = gate.rel_tol * abs(b) + gate.abs_tol
    if gate.better == "higher":
        ok = c >= b - band
    elif gate.better == "lower":
        ok = c <= b + band
    elif gate.better == "equal":
        ok = abs(c - b) <= band
    else:
        return {**v, "ok": False,
                "detail": f"unknown direction {gate.better!r}"}
    out = {**v, "baseline": b, "candidate": c, "band": band, "ok": ok}
    if not ok and gate.note:
        out["detail"] = gate.note
    return out


def run_gate(baseline_dir: str, candidate_dir: str) -> List[dict]:
    """Every verdict for every gated file.  Raises OSError /
    json.JSONDecodeError on an unreadable BASELINE (exit-2 class);
    unreadable candidates are per-file regressions (exit-1 class)."""
    verdicts = []
    for fname, gates in GATES.items():
        with open(os.path.join(baseline_dir, fname)) as f:
            baseline = json.load(f)
        try:
            with open(os.path.join(candidate_dir, fname)) as f:
                candidate = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            for gate in gates:
                verdicts.append({
                    "path": f"{fname}:{gate.path}", "better": gate.better,
                    "ok": False,
                    "detail": f"candidate file unreadable: {e}"})
            continue
        for gate in gates:
            v = check_gate(gate, baseline, candidate)
            v["path"] = f"{fname}:{v['path']}"
            verdicts.append(v)
    return verdicts


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python scripts/bench_gate.py",
        description=__doc__.split("\n")[0])
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap.add_argument("--baseline-dir", default=repo,
                    help="committed baselines (default: repo root)")
    ap.add_argument("--candidate-dir", default=None,
                    help="freshly produced BENCH files (default: the "
                         "baseline dir — the self-check mode)")
    ap.add_argument("--json", action="store_true",
                    help="emit verdicts as JSON")
    args = ap.parse_args(argv)
    candidate = args.candidate_dir or args.baseline_dir
    try:
        verdicts = run_gate(args.baseline_dir, candidate)
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: cannot read baseline: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(verdicts, indent=2))
    else:
        for v in verdicts:
            state = "PASS" if v["ok"] else "FAIL"
            if "candidate" in v:
                print(f"GATE {state}: {v['path']}: {v['candidate']:g} "
                      f"vs baseline {v['baseline']:g} "
                      f"(better={v['better']}, band={v['band']:g})"
                      + (f"  ({v['detail']})" if v.get("detail") else ""))
            else:
                print(f"GATE {state}: {v['path']}: "
                      f"{v.get('detail', 'missing')}")
    bad = [v for v in verdicts if not v["ok"]]
    if bad:
        print(f"{len(bad)} of {len(verdicts)} gates FAILED",
              file=sys.stderr)
        return 1
    print(f"all {len(verdicts)} bench gates hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
