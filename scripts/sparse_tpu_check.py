#!/usr/bin/env python
"""Validate the sparse (BCOO) training path on REAL TPU hardware.

The BCOO gather/segment-sum lowering is CPU-proven by the test suite; this
script is the hardware leg: train hinge+L1 on RCV1-shaped data on the TPU
and cross-check the trajectory against the (trusted) CPU result computed in
a subprocess.  Writes SPARSE_TPU_CHECK.json for the record.

Run it when the tunnel is up:  python scripts/sparse_tpu_check.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "SPARSE_TPU_CHECK.json")

N, D, NNZ, ITERS = 50_000, 47_236, 75, 20

_CHILD = r"""
import os, sys, json, time
if os.environ.get("SPARSE_CHECK_CPU"):
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax; jax.config.update("jax_platforms", "cpu")
else:
    import jax
import numpy as np, jax.numpy as jnp
sys.path.insert(0, %(repo)r)
from tpu_sgd import GradientDescent, L1Updater
from tpu_sgd.ops.gradients import HingeGradient
from tpu_sgd.utils.mlutils import rcv1_like_data

X, y, _ = rcv1_like_data(%(n)d, d=%(d)d, nnz_per_row=%(nnz)d, seed=7)
opt = (GradientDescent(HingeGradient(), L1Updater())
       .set_step_size(100.0).set_num_iterations(%(iters)d)
       .set_reg_param(1e-5).set_mini_batch_fraction(0.5).set_seed(11))
t0 = time.perf_counter()
w, hist = opt.optimize_with_history((X, jnp.asarray(y)), jnp.zeros((%(d)d,)))
jax.block_until_ready(w)
out = {
    "platform": jax.devices()[0].platform,
    "device": str(jax.devices()[0].device_kind),
    "wall_s": round(time.perf_counter() - t0, 3),
    "losses": [round(float(x), 6) for x in np.asarray(hist)],
}
print("RESULT::" + json.dumps(out))
"""


def _run(cpu: bool, timeout: int) -> dict:
    env = dict(os.environ)
    if cpu:
        env["SPARSE_CHECK_CPU"] = "1"
    else:
        env.pop("SPARSE_CHECK_CPU", None)  # a stale flag must not silently
        # turn the TPU leg into a CPU-vs-CPU comparison
    code = _CHILD % {"repo": REPO, "n": N, "d": D, "nnz": NNZ, "iters": ITERS}
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, timeout=timeout,
        capture_output=True, text=True,
    )
    for line in proc.stdout.splitlines():
        if line.startswith("RESULT::"):
            return json.loads(line[len("RESULT::"):])
    raise RuntimeError(
        f"no result (rc={proc.returncode}):\n{proc.stderr[-2000:]}"
    )


def main() -> int:
    print(f"sparse hardware check: n={N} d={D} nnz/row={NNZ}", flush=True)
    tpu = _run(cpu=False, timeout=1200)
    print(f"tpu side: {tpu['device']} ({tpu['platform']}), "
          f"{tpu['wall_s']}s, final loss {tpu['losses'][-1]}", flush=True)
    if tpu["platform"] == "cpu":
        print("TPU leg fell back to CPU (tunnel down?); aborting before "
              "the long CPU cross-check", flush=True)
        return 1
    cpu = _run(cpu=True, timeout=3600)
    print(f"cpu side: {cpu['wall_s']}s, final loss {cpu['losses'][-1]}",
          flush=True)
    import numpy as np

    agree = bool(np.allclose(tpu["losses"], cpu["losses"],
                             rtol=2e-2, atol=1e-3))
    record = {
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "note": (
            "correctness check, not a perf claim: walls are launch-tax-"
            "dominated tiny workloads through the remote-TPU tunnel "
            "(~65 ms fixed dispatch tax per program) and CPU may read "
            "faster than TPU here"
        ),
        "workload": {"n": N, "d": D, "nnz_per_row": NNZ, "iters": ITERS},
        "tpu": tpu,
        "cpu": cpu,
        "trajectories_agree": agree,
    }
    with open(OUT, "w") as f:
        json.dump(record, f, indent=1)
    print(f"trajectories agree: {agree}; wrote {OUT}", flush=True)
    return 0 if agree and tpu["platform"] != "cpu" else 1


if __name__ == "__main__":
    sys.exit(main())
