#!/usr/bin/env python
"""Where does the 0.024 ms aligned-gram iteration go — and what's under it?

Round-4 captures pin the block-aligned sufficient-statistics iteration at
0.0243–0.0246 ms (two cycles, ±0.2%).  Its HBM traffic floor is two
(d, d) f32 prefix reads ≈ 8 MB ≈ 0.011 ms at the measured ~730 GB/s — so
roughly HALF the iteration is something else (while_loop bookkeeping:
loss-history scatter, convergence norms, carry threading).  This
experiment measures, on hardware, three variants of the SAME aligned
window-gradient math driven by the SAME per-iteration key sequence:

  a) full      — the shipped ``make_run`` contract (loss history, realized
                 counts, convergence check): the baseline the bench quotes.
  b) bare      — a ``fori_loop`` carrying only ``w``: the window math with
                 zero bookkeeping.  The floor the driver could approach if
                 history/convergence were opt-out.
  c) chunked   — two-level: an outer scan gathers k iterations' prefix
                 slices into one (k, d, d) buffer per endpoint, an inner
                 fori runs k updates from the gathered stats.  Same bytes,
                 amortized dispatch.

All three must land on the SAME final weights (the window sequence is
identical; (b)/(c) reproduce ``make_step``'s fold_in/randint stream).
Writes GRAM_SCAN_EXPERIMENT.json.  Purely exploratory — the product path
is untouched; a winning variant becomes a round-5 product change.

Run when the tunnel is up:  python scripts/gram_scan_experiment.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "GRAM_SCAN_EXPERIMENT.json")

ROWS = int(os.environ.get("EXP_ROWS", "2998272"))  # bench slab, 2048-aligned
DIM = int(os.environ.get("EXP_DIM", "1000"))
BLOCK = int(os.environ.get("EXP_BLOCK", "4096"))
FRAC = 0.1
STEP = 0.5
SEED = 42
K_CHUNK = int(os.environ.get("EXP_K", "16"))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> int:
    from tpu_sgd.utils.platform import honor_cpu_env

    honor_cpu_env()
    import jax
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    log(f"device: {jax.devices()[0].device_kind} ({platform})")

    from bench import fit_steady_state
    from tpu_sgd.config import SGDConfig
    from tpu_sgd.ops.gram import GramLeastSquaresGradient
    from tpu_sgd.ops.updaters import SimpleUpdater
    from tpu_sgd.optimize.gradient_descent import make_run

    # device-side data generation (no transfer), then one resident build
    key = jax.random.PRNGKey(0)
    kx, kw, kn = jax.random.split(key, 3)

    @jax.jit
    def gen():
        X = jax.random.normal(kx, (ROWS, DIM), jnp.bfloat16)
        w_true = jax.random.uniform(kw, (DIM,), jnp.float32, -1.0, 1.0)
        y = (X.astype(jnp.float32) @ w_true
             + 0.1 * jax.random.normal(kn, (ROWS,), jnp.float32))
        return X, y

    X, y = jax.block_until_ready(gen())
    t0 = time.perf_counter()
    gg = GramLeastSquaresGradient.build(X, y, block_rows=BLOCK, aligned=True)
    jax.block_until_ready(gg.data.PG)
    log(f"stats built in {time.perf_counter() - t0:.1f}s "
        f"(prefix {gg.data.PG.nbytes / 1e9:.2f} GB)")
    # Re-bundle as a VIRTUAL GramData (X=None) so the ~6 GB row slab can
    # actually be freed — every variant below is row-free (aligned windows
    # read only the prefix stacks), and GramData otherwise pins the rows.
    from tpu_sgd.ops.gram import GramData

    d0 = gg.data
    st = GramData(None, d0.PG, d0.Pb, d0.Pyy, d0.G_tot, d0.b_tot,
                  d0.yy_tot, BLOCK,
                  logical_shape=(ROWS, DIM), logical_dtype="bfloat16")
    gg = GramLeastSquaresGradient(st)
    del X, d0
    PG, Pb = st.PG, st.Pb
    nbf = ROWS // BLOCK
    m = max(1, round(FRAC * ROWS))
    mb = max(1, min(nbf, round(m / BLOCK)))
    count = float(mb * BLOCK)
    base_key = jax.random.PRNGKey(SEED)

    def k1_of(i):
        # EXACTLY make_step's sliced-window stream: fold_in(key, i) ->
        # randint start -> clip to block index (ops/gram.py aligned mode)
        k = jax.random.fold_in(base_key, i)
        start = jax.random.randint(k, (), 0, max(1, ROWS - m + 1))
        start = jnp.clip(start, 0, max(ROWS - m, 0))
        return jnp.clip(start // BLOCK, 0, nbf - mb)

    def update(w, i, Gw_minus_b):
        # SimpleUpdater: w - step/sqrt(t) * grad_mean
        lr = STEP / jnp.sqrt(i.astype(jnp.float32))
        return w - lr * (Gw_minus_b / count)

    def window_terms(w, k1, PGa, Pba):
        # stats arrive as ARGUMENTS, never closure constants — GB-scale
        # captured arrays choke remote lowering (ops/gram.py plumbing note)
        k2 = k1 + mb
        PG1 = jax.lax.dynamic_slice_in_dim(PGa, k1, 1, 0)[0]
        PG2 = jax.lax.dynamic_slice_in_dim(PGa, k2, 1, 0)[0]
        Pb1 = jax.lax.dynamic_slice_in_dim(Pba, k1, 1, 0)[0]
        Pb2 = jax.lax.dynamic_slice_in_dim(Pba, k2, 1, 0)[0]
        Gw = jnp.dot((PG2 - PG1), w, precision=jax.lax.Precision.HIGHEST,
                     preferred_element_type=jnp.float32)
        return Gw - (Pb2 - Pb1)

    # ---- (a) full shipped contract --------------------------------------
    def run_full(iters):
        cfg = SGDConfig(step_size=STEP, num_iterations=iters,
                        mini_batch_fraction=FRAC, convergence_tol=0.0,
                        sampling="sliced", seed=SEED)
        run = jax.jit(make_run(gg, SimpleUpdater(), cfg))
        w0 = jnp.zeros((DIM,), jnp.float32)
        t0 = time.perf_counter()
        jax.block_until_ready(run(w0, st, y))
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        w, losses, n_rec = jax.block_until_ready(run(w0, st, y))
        return time.perf_counter() - t0, compile_s, w

    # ---- (b) bare fori_loop: w-only carry, DYNAMIC trip count -----------
    # (one compile serves the whole ladder — compile minutes through the
    # remote tunnel dominate this experiment's wall otherwise)
    @jax.jit
    def run_bare(w0, n, PGa, Pba):
        def body(t, w):
            i = t + 1
            return update(w, i, window_terms(w, k1_of(i), PGa, Pba))

        return jax.lax.fori_loop(0, n, body, w0)

    # ---- (c) chunked gather: outer fori over chunks of K ----------------
    @jax.jit
    def run_chunked(w0, n_chunks, PGa, Pba):
        K = K_CHUNK

        def chunk(c, w):
            idx = c * K + jnp.arange(1, K + 1)  # iteration numbers
            k1s = jax.vmap(k1_of)(idx)
            G1 = jnp.take(PGa, k1s, axis=0)       # (K, d, d) gathers
            G2 = jnp.take(PGa, k1s + mb, axis=0)
            b1 = jnp.take(Pba, k1s, axis=0)
            b2 = jnp.take(Pba, k1s + mb, axis=0)
            Gd = G2 - G1
            bd = b2 - b1

            def inner(t, w):
                Gw = jnp.dot(Gd[t], w,
                             precision=jax.lax.Precision.HIGHEST,
                             preferred_element_type=jnp.float32)
                return update(w, idx[t], Gw - bd[t])

            return jax.lax.fori_loop(0, K, inner, w)

        return jax.lax.fori_loop(0, n_chunks, chunk, w0)

    def time_variant(name, run, iters_list, iters_to_arg):
        pts = []
        w_last = None
        w0 = jnp.zeros((DIM,), jnp.float32)
        t0 = time.perf_counter()
        jax.block_until_ready(run(w0, iters_to_arg(iters_list[0]), PG, Pb))
        compile_total = time.perf_counter() - t0
        log(f"{name}: compile+first {compile_total:.1f}s")
        for iters in iters_list:
            t0 = time.perf_counter()
            w_last = jax.block_until_ready(
                run(w0, iters_to_arg(iters), PG, Pb))
            pts.append((iters, time.perf_counter() - t0))
        slope, fixed, fit = fit_steady_state(pts)
        log(f"{name}: {slope * 1e3:.4f} ms/iter (+{fixed * 1e3:.0f} ms "
            f"launch; residuals {fit['residual_ms']} ms)")
        return slope, fit, np.asarray(w_last)

    ladder = (1200, 3600, 14400)
    assert all(n % K_CHUNK == 0 for n in ladder), (
        f"ladder {ladder} must divide K_CHUNK={K_CHUNK} or the chunked "
        "variant silently drops iterations"
    )
    dt_full, compile_full, w_full = run_full(ladder[0])
    log(f"full: compile+first {compile_full:.1f}s")
    pts_full = [(ladder[0], dt_full)]
    for it in ladder[1:]:
        dt, _, w_full = run_full(it)
        pts_full.append((it, dt))
    slope_a, fixed_a, fit_a = fit_steady_state(pts_full)
    log(f"full: {slope_a * 1e3:.4f} ms/iter (residuals "
        f"{fit_a['residual_ms']} ms)")
    w_a = np.asarray(w_full)

    slope_b, fit_b, w_b = time_variant(
        "bare", run_bare, ladder, lambda n: jnp.asarray(n, jnp.int32))
    slope_c, fit_c, w_c = time_variant(
        "chunked", run_chunked, ladder,
        lambda n: jnp.asarray(n // K_CHUNK, jnp.int32))

    # ---- (d) the PRODUCT chunked driver: full contract, bulk gathers ----
    # (round 5, optimize/gram_driver.py — what set_gram_options(
    # chunk_iters=K) actually ships; measures whether the gather win
    # survives the loss-history/convergence bookkeeping)
    from tpu_sgd.optimize.gram_driver import make_chunked_gram_run

    def run_product(iters):
        cfg = SGDConfig(step_size=STEP, num_iterations=iters,
                        mini_batch_fraction=FRAC, convergence_tol=0.0,
                        sampling="sliced", seed=SEED)
        run = jax.jit(make_chunked_gram_run(
            SimpleUpdater(), cfg, n=ROWS, block_rows=BLOCK,
            chunk_iters=K_CHUNK))
        w0 = jnp.zeros((DIM,), jnp.float32)
        t0 = time.perf_counter()
        jax.block_until_ready(run(w0, st, y))
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        w, losses, n_rec = jax.block_until_ready(run(w0, st, y))
        return time.perf_counter() - t0, compile_s, w

    dt_d, compile_d, w_d = run_product(ladder[0])
    log(f"product-chunked: compile+first {compile_d:.1f}s")
    pts_d = [(ladder[0], dt_d)]
    for it in ladder[1:]:
        dt, _, w_d = run_product(it)
        pts_d.append((it, dt))
    slope_d, fixed_d, fit_d = fit_steady_state(pts_d)
    log(f"product-chunked: {slope_d * 1e3:.4f} ms/iter (residuals "
        f"{fit_d['residual_ms']} ms)")
    w_d = np.asarray(w_d)

    # trajectory agreement: same window stream + same math -> same weights
    agree_b = bool(np.allclose(w_b, w_a, rtol=1e-4, atol=1e-5))
    agree_c = bool(np.allclose(w_c, w_a, rtol=1e-4, atol=1e-5))
    agree_d = bool(np.allclose(w_d, w_a, rtol=1e-4, atol=1e-5))
    log(f"weights agree: bare={agree_b} chunked={agree_c} "
        f"product={agree_d} "
        f"(max|dw| bare {np.abs(w_b - w_a).max():.2e}, chunked "
        f"{np.abs(w_c - w_a).max():.2e}, product "
        f"{np.abs(w_d - w_a).max():.2e})")

    # THE follow-up gate (ISSUE 5 satellite): the chunked driver may
    # only take the planner default if it BEATS the per-iteration
    # contract AND reproduces its trajectory — a fast-but-divergent
    # variant is not a candidate.  The verdict is recorded either way
    # so the JSON closes its own follow-up.
    product_wins = bool(agree_d and slope_d < slope_a)
    verdict = (
        "product_chunked WINS with weights_agree — flip the planner "
        "default to chunk_iters (optimize/gram_driver.py)"
        if product_wins else
        f"product_chunked LOSES ({slope_d * 1e3:.3f} vs "
        f"{slope_a * 1e3:.4f} ms/iter"
        + ("" if agree_d else "; trajectories DIVERGE")
        + ") — planner default stays the per-iteration driver; "
        "chunk_iters remains opt-in"
    )
    log(f"verdict: {verdict}")

    record = {
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "platform": platform,
        "note": (
            "decomposition of the aligned-gram iteration; "
            "product_chunked is the SHIPPED chunked driver "
            "(set_gram_options(chunk_iters=K), optimize/gram_driver.py) "
            "— the weights_agree-gated comparison against full_contract "
            "decides the planner default (see verdict)"
        ),
        "workload": {"rows": ROWS, "dim": DIM, "block_rows": BLOCK,
                     "frac": FRAC, "k_chunk": K_CHUNK},
        "full_contract_ms": slope_a * 1e3,
        "full_fit": fit_a,
        "bare_ms": slope_b * 1e3,
        "bare_fit": fit_b,
        "chunked_ms": slope_c * 1e3,
        "chunked_fit": fit_c,
        "product_chunked_ms": slope_d * 1e3,
        "product_chunked_fit": fit_d,
        "bookkeeping_ms": (slope_a - slope_b) * 1e3,
        "weights_agree": {"bare": agree_b, "chunked": agree_c,
                          "product": agree_d},
        "product_chunked_wins": product_wins,
        "verdict": verdict,
    }
    if platform == "cpu":
        log("CPU fallback: not persisting")
        print(json.dumps(record))
        return 1
    with open(OUT, "w") as f:
        json.dump(record, f, indent=1)
    log(f"wrote {OUT}")
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
