#!/bin/bash
# Round-long TPU tunnel watcher (VERDICT r1 #1: "re-probe every ~10 min from
# a killable subprocess, run the moment the tunnel answers").
#
# Probes the axon tunnel from a timeout-wrapped child process; the moment it
# answers, runs the kernel sweep and the full benchmark (which persists its
# hardware result to BENCH_LAST_TPU.json immediately), then keeps watching
# so a later, healthier tunnel can refresh the numbers.
#
# Usage: nohup bash scripts/tpu_watch.sh >> tpu_watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
PROBE_TIMEOUT="${PROBE_TIMEOUT:-300}"
SLEEP_BETWEEN="${SLEEP_BETWEEN:-300}"
MAX_HOURS="${MAX_HOURS:-11}"
deadline=$(( $(date +%s) + MAX_HOURS * 3600 ))

ran_bench=0
while [ "$(date +%s)" -lt "$deadline" ]; do
  if timeout "$PROBE_TIMEOUT" python -c \
      "import jax; assert jax.devices()[0].platform != 'cpu'" 2>/dev/null; then
    echo "[$(date +%H:%M:%S)] TUNNEL ALIVE"
    # Round-4 capture set (VERDICT r3 #4/#5).  Order: bench FIRST — the
    # headline with the NEW >=3-point regression fit is this round's
    # capture deliverable, and bench persists it before anything long —
    # then the quick correctness checks (whose fresh artifacts carry the
    # new launch-tax note field), then the streamed-statistics true-size
    # measurement.  The settled pallas/kernel sweep and profiler
    # decomposition are skipped (round-3 verdicts stand; BENCH_PALLAS=0
    # carries their records forward).
    echo "[$(date +%H:%M:%S)] full bench (new multi-point fit; pallas records carried forward):"
    BENCH_TPU_RETRIES=2 BENCH_TPU_BACKOFF=30 BENCH_PALLAS=0 BENCH_CHUNKS= \
      timeout 3600 python bench.py 2>&1 | tee -a bench_logs/BENCH_STDERR_r04_tpu.txt
    echo "[$(date +%H:%M:%S)] sparse hardware check:"
    timeout 1800 python scripts/sparse_tpu_check.py 2>&1 | tee sparse_check_watch.log
    echo "[$(date +%H:%M:%S)] quasi-newton/streaming hardware check:"
    timeout 1800 python scripts/quasi_newton_tpu_check.py 2>&1 | tee qn_check_watch.log
    echo "[$(date +%H:%M:%S)] streamed sufficient-stats 10Mx1000 (one-pass build, then device-speed iters):"
    timeout 4500 python scripts/stream_gram_tpu_check.py 2>&1 \
      | tee -a bench_logs/STREAM_GRAM_r04_tpu.txt
    ran_bench=1
    echo "[$(date +%H:%M:%S)] capture set done (BENCH_LAST_TPU.json, SPARSE_TPU_CHECK.json, QUASI_NEWTON_TPU_CHECK.json)"
    # One successful capture is the deliverable; after that, re-check only
    # hourly in case a healthier tunnel can improve the numbers.
    sleep 3600
  else
    echo "[$(date +%H:%M:%S)] tunnel wedged (probe >${PROBE_TIMEOUT}s or failed)"
    sleep "$SLEEP_BETWEEN"
  fi
done
echo "[$(date +%H:%M:%S)] watcher deadline reached (ran_bench=$ran_bench)"
