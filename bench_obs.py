"""Observability overhead benchmark: what tracing+counters cost on the
streamed-superstep and device-resident hot paths.

The claim under test (ISSUE 8 acceptance): with the full production
observability config ON — span tracing to a real ``JsonLinesEventLog``
plus the runtime counter patches — the warmed hot paths show **ZERO
additional dispatches, compiles, or host syncs** versus disabled.  The
disabled baseline is measured by the ``tpu_sgd.analysis`` runtime twins
(``count_dispatches`` / ``count_host_syncs``); the enabled run is
measured by the promoted counters themselves (``tpu_sgd.obs.counters``
— the twins' machinery running as the production accounting layer), and
the numbers must agree exactly.  Any nonzero delta fails the bench
loudly.

Headline metrics are the **count deltas** (and the measured
disabled-hook cost in nanoseconds), NOT wall-clock: this 2-core harness
shares one DRAM wall between host and kernel and drowns millisecond
timing in ambient noise (ROADMAP harness policy; the
BENCH_SUPERSTEP.json basis note).  Wall-clock deltas are reported as
SECONDARY with explicit basis strings: the enabled config's wall
overhead is real but structural — counting launches requires declining
jit's C++ fastpath, so every dispatch takes the Python path — and is
the price of the accounting, not of the span machinery (spans alone,
counters off, ride the same dispatch path as disabled).

Writes ``BENCH_OBS.json``; env knobs: ``OBS_ROWS``, ``OBS_DIM``,
``OBS_ITERS``, ``OBS_K``, ``OBS_C``, ``OBS_REPS``.
"""

import json
import os
import statistics
import sys
import tempfile
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_cpu_multi_thread_eigen=false"
).strip()

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "BENCH_OBS.json")

ROWS = int(os.environ.get("OBS_ROWS", "20000"))
DIM = int(os.environ.get("OBS_DIM", "32"))
ITERS = int(os.environ.get("OBS_ITERS", "640"))
K = int(os.environ.get("OBS_K", "8"))
C = int(os.environ.get("OBS_C", "16"))
REPS = int(os.environ.get("OBS_REPS", "5"))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def dataset():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(ROWS, DIM)).astype(np.float32)
    w = rng.uniform(-1, 1, DIM).astype(np.float32)
    y = (X @ w + 0.01 * rng.normal(size=ROWS)).astype(np.float32)
    return X, y


def run_stream(X, y, k, c):
    """One full-batch host-streamed run on the REAL driver stack;
    returns (weights, wall seconds)."""
    from tpu_sgd.config import SGDConfig
    from tpu_sgd.ops.gradients import LeastSquaresGradient
    from tpu_sgd.ops.updaters import SimpleUpdater
    from tpu_sgd.optimize.streamed import optimize_host_streamed

    cfg = SGDConfig(step_size=0.01, num_iterations=ITERS,
                    mini_batch_fraction=1.0, convergence_tol=0.0,
                    sampling="bernoulli", seed=42)
    t0 = time.perf_counter()
    w, _ = optimize_host_streamed(
        LeastSquaresGradient(), SimpleUpdater(), cfg, X, y,
        np.zeros(DIM, np.float32), superstep_k=k, resident_cadence=c)
    dt = time.perf_counter() - t0
    return np.asarray(w), dt


def measure_path(name, X, y, k, c, trace_dir):
    """Counts + walls for one hot path, obs OFF then obs ON."""
    from tpu_sgd import obs
    from tpu_sgd.analysis.runtime import count_dispatches, count_host_syncs
    from tpu_sgd.obs import counters as obs_counters
    from tpu_sgd.utils.events import JsonLinesEventLog

    log(f"[{name}] warm + disabled baseline ...")
    w_warm, _ = run_stream(X, y, k, c)  # compile everything
    # the disabled compile baseline rides the same jax.monitoring
    # funnel the enabled counters listen on (NOT zero: the streamed
    # driver backend-compiles one small per-run program even warmed —
    # a pre-existing cost the delta must not blame on obs)
    from jax._src import monitoring as _monitoring

    compiles_off = [0]

    def _listener(ev_name, dur, **kw):
        if ev_name.endswith("backend_compile_duration"):
            compiles_off[0] += 1

    _monitoring.register_event_duration_secs_listener(_listener)
    try:
        with count_host_syncs() as sc, count_dispatches() as dc:
            w_off, _ = run_stream(X, y, k, c)
    finally:
        _monitoring._unregister_event_duration_listener_by_callback(
            _listener)
    off = {"dispatches": dc["n"], "host_syncs": sc["n"],
           "compiles": compiles_off[0]}
    np.testing.assert_array_equal(w_off, w_warm)
    walls_off = [run_stream(X, y, k, c)[1] for _ in range(REPS)]

    log(f"[{name}] enabled (tracing -> JSONL + counters) ...")
    trace = os.path.join(trace_dir, f"{name}.jsonl")
    obs.enable(trace)
    try:
        # enable() drops the C++ fastpath cache entries; one run
        # re-traces them (no XLA recompile — asserted below) so the
        # counted/timed runs compare steady state to steady state
        run_stream(X, y, k, c)
        obs_counters.reset()
        w_on, _ = run_stream(X, y, k, c)
        snap = obs_counters.snapshot()
        walls_on = [run_stream(X, y, k, c)[1] for _ in range(REPS)]
    finally:
        obs.disable()
    np.testing.assert_array_equal(w_on, w_warm)
    spans = sum(1 for r in JsonLinesEventLog.read(trace)
                if r.get("kind") == "trace_span")

    def total(kind):
        return sum(v["n"] for key, v in snap.items()
                   if key.endswith("." + kind))

    on = {"dispatches": total("dispatch"),
          "host_syncs": total("host_sync"),
          "compiles": total("compile")}
    deltas = {k: on[k] - off[k] for k in on}
    # THE acceptance gate: observability must be structurally free
    assert deltas == {"dispatches": 0, "host_syncs": 0, "compiles": 0}, (
        f"{name}: enabled obs changed the runtime-event counts: {deltas} "
        f"(off={off}, on={on})")
    log(f"[{name}] deltas all ZERO (off={off}); "
        f"{spans} spans emitted per run")
    return {
        "counts_disabled": off,
        "counts_enabled": on,
        "count_deltas_enabled_minus_disabled": deltas,
        # the trace holds REPS+2 runs: the post-enable re-warm, the
        # counted run, and the REPS timed runs
        "trace_spans_per_run": spans // (REPS + 2),
        "wall_s_disabled": [round(t, 5) for t in walls_off],
        "wall_s_enabled": [round(t, 5) for t in walls_on],
        "wall_median_disabled_s": round(statistics.median(walls_off), 5),
        "wall_median_enabled_s": round(statistics.median(walls_on), 5),
        "wall_overhead_per_iter_us": round(
            (statistics.median(walls_on) - statistics.median(walls_off))
            / ITERS * 1e6, 2),
    }


def disabled_hook_cost_ns():
    """The measured no-op: ns per disabled span()/event()/inc() call."""
    from tpu_sgd.obs import counters as obs_counters
    from tpu_sgd.obs import spans as obs_spans

    n = 500_000
    out = {}
    for label, fn in (
            ("span", lambda: obs_spans.span("train.step")),
            ("event", lambda: obs_spans.event("reliability.retry")),
            ("inc", lambda: obs_counters.inc("train.io_callback"))):
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        out[label] = round((time.perf_counter() - t0) / n * 1e9, 1)
    return out


def main():
    log(f"obs bench: {ROWS}x{DIM} f32 full batch, {ITERS} iters, "
        f"K={K}, C={C}, reps={REPS}")
    X, y = dataset()
    hooks_ns = disabled_hook_cost_ns()
    log(f"disabled hook cost: {hooks_ns} ns/call")
    with tempfile.TemporaryDirectory() as trace_dir:
        superstep = measure_path("superstep", X, y, K, 0, trace_dir)
        resident = measure_path("resident", X, y, K, C, trace_dir)

    doc = {
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "harness": "cpu",
        "workload": {"rows": ROWS, "dim": DIM, "iters": ITERS,
                     "full_batch": True, "k": K, "cadence": C,
                     "reps": REPS},
        "headline": {
            "basis": (
                "count deltas (enabled minus disabled) measured by the "
                "analysis runtime twins (disabled) and the promoted "
                "obs.counters (enabled) on warmed drivers; counts are "
                "exact and noise-immune — the 2-core harness policy. "
                "Disabled hook cost is the per-call price every "
                "production process pays when nobody opts in."),
            "superstep_count_deltas":
                superstep["count_deltas_enabled_minus_disabled"],
            "resident_count_deltas":
                resident["count_deltas_enabled_minus_disabled"],
            "disabled_hook_cost_ns_per_call": hooks_ns,
        },
        "secondary_wall_clock": {
            "basis": (
                "median of REPS end-to-end runs, quiet-as-available "
                "2-core CPU host; enabled overhead is dominated by "
                "declining jit's C++ fastpath so dispatches stay "
                "countable (structural, not span cost) plus one JSONL "
                "record write per span; treat as indicative only — "
                "ambient DRAM-wall noise on this harness is the same "
                "order (ROADMAP harness policy; BENCH_SUPERSTEP.json "
                "basis note)"),
            "superstep": {k: superstep[k] for k in (
                "wall_s_disabled", "wall_s_enabled",
                "wall_median_disabled_s", "wall_median_enabled_s",
                "wall_overhead_per_iter_us")},
            "resident": {k: resident[k] for k in (
                "wall_s_disabled", "wall_s_enabled",
                "wall_median_disabled_s", "wall_median_enabled_s",
                "wall_overhead_per_iter_us")},
        },
        "detail": {"superstep": superstep, "resident": resident},
    }
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=1)
    log(f"wrote {OUT}")
    print(json.dumps(doc["headline"], indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
