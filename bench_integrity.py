"""Integrity-plane bench: what do the checksummed wires cost?

Per the 2-core harness policy (ROADMAP), the headline numbers are
COUNTS and BYTES-RATIOS — structural, reproducible — with the checksum
wall measured STAGE-ISOLATED (CRC-32 throughput on frame-sized host
buffers) rather than as end-to-end deltas that ambient CI noise drowns:

* **zero_added_runtime** — the warmed fused streamed driver's
  dispatch/host-sync counts with the integrity plane ON minus OFF:
  both deltas must be ZERO (checksums are pure host work over bytes
  the producers already hold — the PR 8 pin discipline re-asserted as
  a gated bench number, ``scripts/bench_gate.py``).
* **frames_verified_per_run** — how many chunk frames the run sealed
  and verified (deterministic: one per superchunk), from the
  ``integrity.verified.io.chunk`` counter.
* **checksum_overhead_bytes_ratio** — payload bytes moved per run over
  the checksum bytes added (4 per frame): the wire-size price of the
  integrity plane, which is why it defaults ON.
* **crc_stage** — isolated CRC-32 GB/s at representative frame sizes
  (a 1 MiB chunk, a 256 KiB push payload, a 16 KiB top-k segment);
  the seal+verify pair costs two passes at this rate.

Writes ``BENCH_INTEGRITY.json``; ``bench_gate`` bands the headline.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

#: graftlint lock-discipline declaration (tpu_sgd/analysis): EMPTY on
#: purpose — a single-threaded offline bench, no shared state.
GRAFTLINT_LOCKS: dict = {}

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "BENCH_INTEGRITY.json")


def _data(n=768, d=12, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (X @ w_true + 0.01 * rng.normal(size=n)).astype(np.float32)
    return X, y, np.zeros(d, np.float32)


def _opt():
    from tpu_sgd.optimize.gradient_descent import GradientDescent

    return (GradientDescent()
            .set_num_iterations(24).set_step_size(0.1)
            .set_mini_batch_fraction(0.5).set_sampling("sliced")
            .set_convergence_tol(0.0).set_seed(7)
            .set_host_streaming(True).set_superstep(4))


def bench_crc_stage() -> dict:
    """Isolated CRC-32 throughput at frame-representative sizes —
    quietest-attempt selection (min of 5), reps sized so each attempt
    runs long enough to time."""
    from tpu_sgd.io.integrity import checksum_arrays

    out = {}
    for name, nbytes in (("chunk_1mib", 1 << 20),
                         ("push_256kib", 1 << 18),
                         ("segment_16kib", 1 << 14)):
        a = np.random.default_rng(3).random(nbytes // 4).astype(np.float32)
        reps = max(8, int((16 << 20) // nbytes))
        walls = []
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(reps):
                checksum_arrays(a)
            walls.append((time.perf_counter() - t0) / reps)
        w = min(walls)
        out[name] = {
            "frame_bytes": int(a.nbytes),
            "wall_s_per_checksum": w,
            "gb_s": a.nbytes / w / 1e9,
        }
    return out


def bench_zero_added_runtime() -> dict:
    """Warmed fused run: dispatch/sync counts, integrity ON vs OFF —
    the deltas are the headline and must be zero."""
    from tpu_sgd.analysis.runtime import count_dispatches, count_host_syncs
    from tpu_sgd.io.integrity import set_integrity

    X, y, w0 = _data()
    opt = _opt()
    opt.optimize_with_history((X, y), w0)  # warm every program
    with count_host_syncs() as s_on, count_dispatches() as d_on:
        t0 = time.perf_counter()
        opt.optimize_with_history((X, y), w0)
        wall_on = time.perf_counter() - t0
    set_integrity(False)
    try:
        with count_host_syncs() as s_off, count_dispatches() as d_off:
            t0 = time.perf_counter()
            opt.optimize_with_history((X, y), w0)
            wall_off = time.perf_counter() - t0
    finally:
        set_integrity(True)
    return {
        "dispatches_on": d_on["n"], "dispatches_off": d_off["n"],
        "host_syncs_on": s_on["n"], "host_syncs_off": s_off["n"],
        "dispatch_delta": d_on["n"] - d_off["n"],
        "host_sync_delta": s_on["n"] - s_off["n"],
        "wall_on_s": wall_on, "wall_off_s": wall_off,
    }


def bench_frames_and_bytes() -> dict:
    """One obs-observed run: frames verified and wire payload bytes →
    the checksum byte-overhead ratio (4 bytes of CRC per frame)."""
    from tpu_sgd import obs
    from tpu_sgd.obs import counters as obs_counters

    class _Sink:
        def emit(self, kind, payload):
            pass

    X, y, w0 = _data()
    opt = _opt()
    opt.optimize_with_history((X, y), w0)  # warm (compiles off-ledger)
    obs.enable(_Sink())
    try:
        obs_counters.reset()
        opt.optimize_with_history((X, y), w0)
        snap = obs_counters.snapshot()
    finally:
        obs.disable()
    frames = snap.get("integrity.verified.io.chunk", {"n": 0})["n"]
    payload = sum(v["bytes"] for k, v in snap.items()
                  if ".wire." in k and not k.endswith(".logical"))
    overhead = 4 * frames
    return {
        "frames_verified_per_run": frames,
        "wire_payload_bytes_per_run": int(payload),
        "checksum_overhead_bytes": overhead,
        "checksum_overhead_bytes_ratio": (payload / overhead
                                          if overhead else 0.0),
    }


def main() -> int:
    crc = bench_crc_stage()
    zero = bench_zero_added_runtime()
    frames = bench_frames_and_bytes()
    doc = {
        "headline": {
            "zero_added_runtime": {
                "dispatch_delta": zero["dispatch_delta"],
                "host_sync_delta": zero["host_sync_delta"],
            },
            "frames_verified_per_run": frames["frames_verified_per_run"],
            "checksum_overhead_bytes_ratio": round(
                frames["checksum_overhead_bytes_ratio"], 1),
            "crc_gb_s_chunk": round(crc["chunk_1mib"]["gb_s"], 2),
        },
        "detail": {"crc_stage": crc, "zero_added_runtime": zero,
                   "frames": frames},
        "basis": (
            "24-iteration sliced-sampling fused (K=4) host-streamed run "
            "on the 2-core CPU harness; counts/ratios are the headline "
            "per the ROADMAP policy (dispatch/sync deltas integrity-on "
            "minus integrity-off on the warmed driver — MUST be 0; "
            "frame count is one seal+verify per superchunk; byte ratio "
            "is wire payload over 4-byte CRCs).  CRC walls are "
            "stage-isolated min-of-5; end-to-end walls recorded for "
            "context only — ambient-noise-bound on this harness."
        ),
    }
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(json.dumps(doc["headline"], indent=2))
    print(f"wrote {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
