"""Compressed sparse wire benchmark: BCOO feed + top-k/EF segments.

Stage-isolated, counts-first measurement of the compressed wire
(``tpu_sgd/io/sparse_wire.py``; README "Compressed wire") the way
``bench_ingest.py`` measures the dense wire.  HEADLINE numbers are the
structural ones this 2-core harness cannot blur: **wire-bytes ratios**
(physical vs dense-f32-logical, from the ``obs`` wire counters) and
**dispatch/transfer counts** (the analysis twins) on the warmed
host-streamed sparse path.  Wall medians are SECONDARY, with basis
strings saying why (ambient-noise-bound end-to-end walls; host
staging/compress stages are the isolated timings that transfer).

Three sections:

* ``sparse_feed`` — the RCV1-shaped host-streamed BCOO feed: physical
  vs dense-f32 bytes per staged superchunk, host staging wall medians,
  and warmed-run dispatch/h2d counts (one dispatch + 4 component puts
  per K-superstep).
* ``topk_compress`` — the host top-k + error-feedback stage in
  isolation: median compress wall per (d,)-update at several fracs,
  plus the segment bytes ratio.
* ``merge_wire`` — the per-shard streamed-totals merge, dense vs
  compressed (4 shards): physical bytes each way, build walls
  secondary.

Writes ``BENCH_SPARSE_WIRE.json``; env knobs: ``SPW_ROWS``, ``SPW_DIM``,
``SPW_NNZ``, ``SPW_ITERS``, ``SPW_REPS``.
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
    + " --xla_cpu_multi_thread_eigen=false"
).strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402

ROWS = int(os.environ.get("SPW_ROWS", "20000"))
DIM = int(os.environ.get("SPW_DIM", "47236"))  # the RCV1 feature count
NNZ = int(os.environ.get("SPW_NNZ", "48"))     # ~0.1% density
ITERS = int(os.environ.get("SPW_ITERS", "24"))
K = 4
REPS = int(os.environ.get("SPW_REPS", "5"))
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "BENCH_SPARSE_WIRE.json")


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def median(xs):
    return float(np.median(np.asarray(xs)))


def bench_sparse_feed():
    """The host-streamed BCOO feed: bytes, counts, staging walls."""
    from tpu_sgd.analysis.runtime import count_dispatches
    from tpu_sgd.obs import counters as obs_counters
    from tpu_sgd.obs.counters import wire_ratios
    from tpu_sgd.ops.gradients import HingeGradient
    from tpu_sgd.ops.sparse import sparse_data
    from tpu_sgd.optimize.gradient_descent import GradientDescent

    log(f"sparse_feed: {ROWS}x{DIM}, {NNZ} nnz/row, {ITERS} iters K={K}")
    X, y, _ = sparse_data(ROWS, DIM, nnz_per_row=NNZ, kind="svm", seed=0)
    w0 = np.zeros(DIM, np.float32)

    def mk():
        return (GradientDescent(gradient=HingeGradient())
                .set_num_iterations(ITERS).set_step_size(0.2)
                .set_mini_batch_fraction(0.1).set_convergence_tol(0.0)
                .set_seed(7).set_host_streaming(True).set_superstep(K))

    mk().optimize_with_history((X, y), w0)  # warm the fused program

    obs_counters.enable()
    try:
        obs_counters.reset()
        t0 = time.perf_counter()
        mk().optimize_with_history((X, y), w0)
        wall = time.perf_counter() - t0
        snap = obs_counters.snapshot()
    finally:
        obs_counters.disable()
        obs_counters.reset()
    ratios = wire_ratios(snap)
    bcoo = next(r for n, r in ratios.items() if n.endswith(".bcoo"))

    with count_dispatches() as dc:
        mk().optimize_with_history((X, y), w0)

    # isolated host staging wall: one superchunk's CSR gather + pad
    from tpu_sgd.io.sparse_wire import (bcoo_to_csr_host,
                                        plan_sparse_batches,
                                        stage_sparse_batch)

    indptr, cols, vals, _ = bcoo_to_csr_host(X)
    frac = 0.1
    sigma = np.sqrt(ROWS * frac * (1 - frac))
    cap = int(min(ROWS, np.ceil(ROWS * frac + 6 * sigma + 8)))

    def sample_rows(i):
        rng = np.random.default_rng(7 + i)
        m = rng.random(ROWS) < frac
        idx = np.nonzero(m)[0]
        return idx[:cap]

    nse_cap = plan_sparse_batches(indptr, sample_rows, ITERS, cap)
    stage_walls = []
    for rep in range(REPS + 1):
        t0 = time.perf_counter()
        for t in range(K):
            stage_sparse_batch(indptr, cols, vals, sample_rows(1 + t),
                               cap, nse_cap)
        if rep:  # first is warmup
            stage_walls.append(time.perf_counter() - t0)

    # every leaf that crosses, both sides: X rows (+12B/entry sparse,
    # 4B/elem dense) plus the SAME f32 labels and bool valid mask
    dense_super_bytes = K * (cap * (DIM * 4 + 5))
    sparse_super_bytes = K * (nse_cap * 12 + cap * 5)
    return {
        "shape": {"rows": ROWS, "dim": DIM, "nnz_per_row": NNZ,
                  "iters": ITERS, "superstep_k": K,
                  "mini_batch_fraction": frac, "row_cap": cap,
                  "nse_cap": nse_cap},
        "wire_bytes": {
            "physical": bcoo["physical_bytes"],
            "dense_f32_logical": bcoo["logical_bytes"],
            "ratio": bcoo["ratio"],
            "per_superchunk_physical": sparse_super_bytes,
            "per_superchunk_dense_f32": dense_super_bytes,
            "basis": ("obs wire counters over one full run; physical = "
                      "EVERY transferred leaf (BCOO data f32 + int32x2 "
                      "indices + f32 labels + bool valid), logical = "
                      "the dense-f32 chunk with the same labels/mask; "
                      "structural, noise-free"),
        },
        "counts": {
            "dispatches_per_run": dc["n"],
            "supersteps": -(-ITERS // K),
            "basis": ("analysis twins on the warmed run; the fused "
                      "sparse scan is ONE program per superstep (+ the "
                      "per-run re-jit trace, a known streamed-driver "
                      "cost) — counts, not walls, are the headline on "
                      "this 2-core harness"),
        },
        "staging_wall_s": {
            "median_per_superchunk": median(stage_walls),
            "basis": ("host-isolated CSR gather + fixed-shape pad for "
                      f"K={K} batches, {REPS} reps median, warmup "
                      "discarded; runs on the prefetch worker in "
                      "production (overlapped)"),
        },
        "end_to_end_wall_s": {
            "value": wall,
            "basis": ("SECONDARY: counters-enabled run on a noisy "
                      "2-core VM; see two-core overlap-bench policy"),
        },
    }


def bench_topk_compress():
    """Host top-k + EF compress stage in isolation."""
    from tpu_sgd.io.sparse_wire import ErrorFeedback

    out = {}
    rng = np.random.default_rng(1)
    for dim in (DIM, 1_000_000):
        upd = rng.normal(size=dim).astype(np.float32)
        for frac in (0.01, 0.05):
            ef = ErrorFeedback(dim, frac)
            walls = []
            for rep in range(REPS + 1):
                t0 = time.perf_counter()
                idx, vals = ef.compress(upd)
                if rep:
                    walls.append(time.perf_counter() - t0)
            out[f"d{dim}_topk{frac}"] = {
                "median_s": median(walls),
                "segment_bytes": int(idx.nbytes + vals.nbytes),
                "dense_f32_bytes": int(upd.nbytes),
                "ratio": upd.nbytes / (idx.nbytes + vals.nbytes),
            }
    out["basis"] = ("host numpy argpartition select + extract, median "
                    f"of {REPS}, warmup discarded; the stage "
                    "choose_wire_compress weighs against the wire "
                    "saving")
    return out


def bench_merge_wire():
    """Per-shard streamed-totals merge: dense vs compressed bytes."""
    from tpu_sgd.obs import counters as obs_counters
    from tpu_sgd.obs.counters import wire_ratios
    from tpu_sgd.parallel.gram_parallel import build_streamed_total_stats
    from tpu_sgd.parallel.mesh import data_mesh

    mesh = data_mesh(jax.devices()[:4])
    rng = np.random.default_rng(2)
    d = 256
    Xh = rng.normal(size=(4096, d)).astype(np.float32)
    yh = rng.normal(size=4096).astype(np.float32)

    def run(wire_compress):
        obs_counters.enable()
        try:
            obs_counters.reset()
            t0 = time.perf_counter()
            build_streamed_total_stats(mesh, Xh, yh, block_rows=256,
                                       wire_compress=wire_compress)
            wall = time.perf_counter() - t0
            snap = obs_counters.snapshot()
        finally:
            obs_counters.disable()
            obs_counters.reset()
        merge = {n.rsplit(".", 1)[-1]: r
                 for n, r in wire_ratios(snap).items()}
        return wall, merge

    wall_dense, merge_dense = run(None)
    wall_comp, merge_comp = run("topk:0.01")
    dense_phys = merge_dense["dense-f32"]["physical_bytes"]
    comp_phys = (merge_comp["topk"]["physical_bytes"]
                 + merge_comp["dense-f32"]["physical_bytes"])
    return {
        "shards": 4, "d": d,
        "dense_merge_bytes": dense_phys,
        "compressed_merge_bytes": comp_phys,
        "compressed_segments_bytes": merge_comp["topk"]["physical_bytes"],
        "residual_flush_bytes": merge_comp["dense-f32"]["physical_bytes"],
        "ratio": dense_phys / comp_phys,
        "walls_s_secondary": {"dense": wall_dense,
                              "compressed": wall_comp},
        "basis": ("obs wire counters over the k-1 shard merges at "
                  "topk:0.01 + ONE dense residual flush (totals exact "
                  "up to reassociation); with k shards the ratio "
                  "approaches (k-1)/(1 + (k-1)*2*frac) — the win grows "
                  "with the shard count; walls secondary (2-core "
                  "policy)"),
    }


def main():
    doc = {
        "bench": "sparse_wire",
        "jax": jax.__version__,
        "devices": len(jax.devices()),
        "headline": ("wire-bytes ratios (physical vs dense-f32) and "
                     "dispatch counts; walls secondary on this "
                     "2-core harness"),
        "sparse_feed": bench_sparse_feed(),
        "topk_compress": bench_topk_compress(),
        "merge_wire": bench_merge_wire(),
    }
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=2)
    log(f"wrote {OUT}")
    log(f"sparse feed wire ratio: "
        f"{doc['sparse_feed']['wire_bytes']['ratio']:.1f}x; merge ratio: "
        f"{doc['merge_wire']['ratio']:.1f}x")


if __name__ == "__main__":
    main()
